"""Packed actor systems: staging ``ActorModel`` transitions onto the TPU.

The host ``ActorModel`` (``stateright_tpu.actor.model``) enumerates
data-dependent action sets and runs arbitrary Python actor callbacks — the
reference's design (``/root/reference/src/actor/model.rs:214-649``), which
cannot be traced. This module provides the fixed-width staged equivalent
(SURVEY §2.2 names ``ActorModel`` "the prime candidate for the fixed-width
staged transition function"):

- **actor rows**: per-actor state packs into a ``(N, R)`` u32 matrix;
- **network table**: unordered nets use a bounded ``(E,)``-slot envelope
  table (src, dst, msg words, count); identical envelope multisets
  fingerprint identically because the fingerprint view reduces the table
  to an order-insensitive multiset digest (the host hashes networks
  order-insensitively; the commutative digest is the device analog — no
  per-transition sort). Ordered nets use per directed-pair FIFO queues
  ``(N², Q, W)`` with the head always at index 0 (shift-on-consume keeps
  the arrays canonical) — the device analog of the reference's
  ``BTreeMap<(src,dst), VecDeque>`` flows
  (``/root/reference/src/actor/network.rs:46-68``);
- **timers**: one bitmask word per actor;
- **crash faults**: a ``(N,)`` crashed vector when ``max_crashes`` is set;
  excluded from fingerprints via ``packed_fingerprint_view`` to mirror the
  host state hash (reference ``src/actor/model_state.rs:86-97``);
- **dense actions**: Deliver ids (``E`` slots, or ``N²`` flow heads for
  ordered) + Drop ids (lossy only) + ``N×T`` Timeout ids + ``N`` Crash ids
  (when ``max_crashes > 0``), each with a traceable guard;
- **auxiliary history**: codecs with ``history_width > 0`` carry a packed
  history vector updated by traceable ``record_msg_in``/``record_msg_out``
  twins (see ``semantics/packed_linearizability.py``);
- **actor callbacks**: each actor type supplies jax-traceable
  ``on_msg``/``on_timeout`` kernels via an ``ActorPackedCodec``;
  heterogeneous systems dispatch with ``lax.switch``.

The transition semantics mirror the host model exactly — no-op pruning
(``is_no_op``/``is_no_op_with_timer``), deliver-before-send network
effects, fired-timer clearing before command processing — so packed and
host checkers agree on exact state counts (the parity test contract).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..core.batch import BatchableModel
from .actor import Id, Out
from .model import ActorModel
from .model_state import ActorModelState
from .network import (
    Envelope,
    Network,
    ORDERED,
    UNORDERED_DUPLICATING,
    UNORDERED_NONDUPLICATING,
)
from .timers import Timers


class ActorPackedCodec:
    """Model-specific packing contract consumed by ``PackedActorModel``.

    Widths are static; the traceable kernels receive/return u32 arrays:

    - ``on_msg`` branch (one per actor type):
      ``fn(id, row, src, msg) -> (row', sends, set_bits, cancel_bits, changed)``
      with ``id``/``src`` scalar i32, ``row`` ``(R,)`` u32, ``msg`` ``(W,)``
      u32, ``sends`` ``(S, 1+W)`` u32 (column 0 = destination id, or
      ``SEND_NONE`` for unused rows), timer masks scalar u32, ``changed``
      scalar bool (the analog of returning a new state vs ``None``).
    - ``on_timeout`` branch: ``fn(id, row, timer_id) -> same``.
    """

    SEND_NONE = np.uint32(0xFFFFFFFF)

    msg_width: int
    state_width: int
    # timer value -> bit index by position; immutable default (a mutable
    # class-level list would be shared across every codec subclass).
    timer_values: Sequence[Any] = ()
    send_capacity: int
    # Auxiliary history support (the reference's ``H`` type param,
    # ``/root/reference/src/actor/model.rs:23-55``): 0 means "no history".
    # A codec with ``history_width > 0`` packs the model's history into a
    # ``(history_width,)`` u32 vector that rides in the packed state (and
    # therefore the device fingerprint — history distinguishes states, so
    # it must), and supplies the two traceable hooks mirroring the host's
    # ``record_msg_in`` / ``record_msg_out``.
    history_width: int = 0

    # -- host <-> packed conversions --------------------------------------

    def pack_actor_state(self, actor_index: int, state) -> np.ndarray:
        raise NotImplementedError

    def unpack_actor_state(self, actor_index: int, row: np.ndarray):
        raise NotImplementedError

    def pack_msg(self, msg) -> np.ndarray:
        raise NotImplementedError

    def unpack_msg(self, vec: np.ndarray):
        raise NotImplementedError

    def pack_history(self, history) -> np.ndarray:
        raise NotImplementedError

    def unpack_history(self, vec: np.ndarray):
        raise NotImplementedError

    # -- traceable history hooks (history_width > 0 only) -------------------

    def history_on_deliver(self, model, hist, src, dst, msg):
        """``record_msg_in`` analog: applied on Deliver with the envelope
        being delivered, BEFORE send commands are processed (host order)."""
        raise NotImplementedError

    def history_on_send(self, model, hist, src, dst, msg):
        """``record_msg_out`` analog: applied per Send command, in command
        order, to the already-updated history."""
        raise NotImplementedError

    # -- traceable kernels -------------------------------------------------

    def actor_type_id(self, actor_index: int, actor) -> int:
        return 0

    def on_msg_branches(self, model) -> List[Callable]:
        raise NotImplementedError

    def on_timeout_branches(self, model) -> List[Callable]:
        """Timer-free codecs (empty ``timer_values``) may return []."""
        return []

    # -- traceable symmetry hooks (device symmetry reduction only) ----------

    def rewrite_actor_row(self, model, row, old_to_new):
        """Rewrites embedded actor ids inside one packed state row under a
        permutation (``old_to_new[i]`` = the new id of actor ``i``) — the
        traceable twin of the host ``rewrite_value`` recursion over the
        actor state. The default is the identity: correct ONLY for rows
        with no embedded ids. Codecs whose rows carry ids (votes, leader
        hints, …) must override, or device symmetry counts will diverge
        from the host orbit counts (the parity tests' contract)."""
        return row

    def rewrite_msg_ids(self, model, vec, old_to_new):
        """Same, for embedded ids inside one packed message vector."""
        return vec

    # -- traceable model hooks ---------------------------------------------

    def packed_conditions(self, model) -> List[Callable]:
        raise NotImplementedError

    def packed_within_boundary(self, model, state) -> Any:
        import jax.numpy as jnp

        return jnp.bool_(True)

    def packed_row_within_boundary(self, model, row) -> Any:
        """Per-row boundary check for the fps expansion path. Must satisfy
        ``packed_within_boundary(state) == all rows pass this`` — the fps
        wave checks only the row a transition changed (the parent's other
        rows were admitted already, so the check is inductive). Codecs
        overriding ``packed_within_boundary`` with a per-row predicate
        (e.g. Raft's term cap) MUST override this consistently; boundary
        predicates that are not per-row decompositions cannot use the fps
        path."""
        import jax.numpy as jnp

        return jnp.bool_(True)


class PackedActorModel(ActorModel, BatchableModel):
    """An ``ActorModel`` that additionally implements the packed protocol.

    Build it exactly like an ``ActorModel`` (``.actor()``,
    ``.init_network()``, ``.property()``, …) and attach a codec; the packed
    side is validated lazily on first use so host-only checking of
    unsupported configurations still works.
    """

    def __init__(self, codec: ActorPackedCodec, cfg=None, init_history=None):
        super().__init__(cfg=cfg, init_history=init_history)
        self.codec = codec
        self.envelope_capacity = 32
        self.flow_capacity = 8
        self.flow_pairs = None

    def with_envelope_capacity(self, capacity: int) -> "PackedActorModel":
        """Sets the network table's slot count (unordered networks). Must
        upper-bound the reachable distinct-envelope count: overflowing
        transitions are pruned, which the exact-count parity tests surface
        as a mismatch."""
        self.envelope_capacity = capacity
        return self

    def with_flow_capacity(self, capacity: int) -> "PackedActorModel":
        """Sets the per-flow FIFO depth (ordered networks); analogous
        overflow semantics to ``with_envelope_capacity``."""
        self.flow_capacity = capacity
        return self

    def with_flow_pairs(self, pairs) -> "PackedActorModel":
        """Restricts ordered-network flows to the given directed
        ``(src, dst)`` pairs. The flow arrays and the deliver/drop action
        grid then scale with ``len(pairs)`` instead of ``N^2`` — on the
        3-client ordered ABD register this shrinks the packed state ~4x
        (the N^2 flow table is ~87% of its words, mostly structurally
        dead: register clients never message clients, nobody messages
        itself). A device-side send outside the set behaves as a
        zero-capacity flow (transition pruned — the same boundary
        semantics as ``with_flow_capacity`` overflow, surfaced by the
        exact-count parity tests); host packing of such a state raises.
        Incompatible with full-group ``packed_symmetry`` (the pair set is
        generally not closed under S_N; ``packed_symmetry`` raises)."""
        pairs = [(int(a), int(b)) for a, b in pairs]
        if len(set(pairs)) != len(pairs):
            raise ValueError("flow_pairs contains duplicates")
        self.flow_pairs = pairs
        return self

    def _pair_tables(self):
        """(lookup, src, dst) numpy tables for ordered flows: ``lookup``
        maps ``src*N + dst`` to the flow index (-1 = excluded pair);
        ``src``/``dst`` invert it per flow index. Identity layout when
        ``flow_pairs`` is unset."""
        N = self._N
        if self.flow_pairs is None:
            idx = np.arange(N * N, dtype=np.int32)
            return idx, (idx // N).astype(np.int32), (idx % N).astype(np.int32)
        lookup = np.full((N * N,), -1, np.int32)
        src = np.zeros((len(self.flow_pairs),), np.int32)
        dst = np.zeros_like(src)
        for k, (a, b) in enumerate(self.flow_pairs):
            if not (0 <= a < N and 0 <= b < N):
                raise ValueError(f"flow pair {(a, b)} out of range for N={N}")
            lookup[a * N + b] = k
            src[k], dst[k] = a, b
        return lookup, src, dst

    # -- validation --------------------------------------------------------

    def _packed_check(self):
        if self.init_history is not None and not self.codec.history_width:
            raise NotImplementedError(
                "this codec does not pack auxiliary history (declare "
                "history_width and the history hooks to stage it on device)"
            )
        # Non-empty initial networks need no special staging: host
        # ``init_states`` seeds the ``ActorModelState`` network from
        # ``init_network`` (reference ``src/actor/model.rs:96-100``) plus
        # on-start sends, and ``pack_state`` packs whatever the state's
        # network holds — envelope table and FIFO flows alike. Capacity
        # overflow surfaces as the usual ``ValueError`` at packing time.

    # -- static shape helpers ----------------------------------------------

    @property
    def _N(self) -> int:
        return len(self.actors_list)

    @property
    def _E(self) -> int:
        return self.envelope_capacity

    @property
    def _Q(self) -> int:
        return self.flow_capacity

    @property
    def _P(self) -> int:
        """Directed flow pair count (ordered networks): all ``N^2`` pairs
        laid out as ``src * N + dst``, or the restricted ``flow_pairs``
        list's length."""
        if self.flow_pairs is not None:
            return len(self.flow_pairs)
        return self._N * self._N

    @property
    def _T(self) -> int:
        return len(self.codec.timer_values)

    @property
    def _dup(self) -> bool:
        return self._init_network.kind == UNORDERED_DUPLICATING

    @property
    def _ordered(self) -> bool:
        return self._init_network.kind == ORDERED

    def _timer_bit(self, timer) -> int:
        return self.codec.timer_values.index(timer)

    # -- BatchableModel: shape info ---------------------------------------

    def packed_action_count(self) -> int:
        self._packed_check()
        slots = self._P if self._ordered else self._E
        deliver_drop = slots * (2 if self._lossy_network else 1)
        crash = self._N if self._max_crashes else 0
        return deliver_drop + self._N * self._T + crash

    # -- host <-> packed state conversion ----------------------------------

    def pack_state(self, sys_state: ActorModelState):
        self._packed_check()
        codec = self.codec
        N, E, W, R = self._N, self._E, codec.msg_width, codec.state_width
        rows = np.zeros((N, R), np.uint32)
        for i, actor_state in enumerate(sys_state.actor_states):
            rows[i] = codec.pack_actor_state(i, actor_state)
        timers = np.zeros((N,), np.uint32)
        for i, tset in enumerate(sys_state.timers_set):
            for t in tset:
                timers[i] |= np.uint32(1) << np.uint32(self._timer_bit(t))

        if self._ordered:
            Q, P = self._Q, self._P
            lookup, _, _ = self._pair_tables()
            flow_msg = np.zeros((P, Q, W), np.uint32)
            flow_len = np.zeros((P,), np.uint32)
            for (src, dst), msgs in sys_state.network.data.items():
                if not msgs:
                    continue
                if len(msgs) > Q:
                    raise ValueError(
                        f"flow {src!r}->{dst!r} holds {len(msgs)} messages; "
                        f"flow_capacity={Q} is too small"
                    )
                p = int(lookup[int(src) * N + int(dst)])
                if p < 0:
                    raise ValueError(
                        f"flow {src!r}->{dst!r} holds messages but is not "
                        "in flow_pairs"
                    )
                flow_len[p] = len(msgs)
                for i, m in enumerate(msgs):
                    flow_msg[p, i] = codec.pack_msg(m)
            out = {
                "rows": rows,
                "timers": timers,
                "flow_msg": flow_msg,
                "flow_len": flow_len,
            }
        else:
            envs = []
            if self._init_network.kind == UNORDERED_NONDUPLICATING:
                items = list(sys_state.network.data.items())
            else:
                items = [(env, 1) for env in sys_state.network.data]
            if len(items) > E:
                raise ValueError(
                    f"state has {len(items)} distinct envelopes; "
                    f"envelope_capacity={E} is too small"
                )
            for env, count in items:
                envs.append(
                    (
                        int(env.src),
                        int(env.dst),
                        tuple(int(x) for x in codec.pack_msg(env.msg)),
                        int(count),
                    )
                )
            envs.sort()
            net_src = np.zeros((E,), np.uint32)
            net_dst = np.zeros((E,), np.uint32)
            net_msg = np.zeros((E, W), np.uint32)
            net_cnt = np.zeros((E,), np.uint32)
            for slot, (src, dst, msg, count) in enumerate(envs):
                net_src[slot] = src
                net_dst[slot] = dst
                net_msg[slot] = msg
                net_cnt[slot] = count
            out = {
                "rows": rows,
                "timers": timers,
                "net_src": net_src,
                "net_dst": net_dst,
                "net_msg": net_msg,
                "net_cnt": net_cnt,
            }
        if self._max_crashes:
            out["crashed"] = np.array(
                [1 if c else 0 for c in sys_state.crashed], np.uint32
            )
        if codec.history_width:
            hist = np.asarray(
                codec.pack_history(sys_state.history), np.uint32
            )
            if hist.shape != (codec.history_width,):
                raise ValueError(
                    f"pack_history returned shape {hist.shape}; expected "
                    f"({codec.history_width},)"
                )
            out["hist"] = hist
        return out

    def unpack_state(self, packed) -> ActorModelState:
        codec = self.codec
        rows = np.asarray(packed["rows"])
        timers = np.asarray(packed["timers"])
        actor_states = [
            codec.unpack_actor_state(i, rows[i]) for i in range(self._N)
        ]
        timers_set = []
        for i in range(self._N):
            tset = Timers()
            for b, timer in enumerate(codec.timer_values):
                if int(timers[i]) & (1 << b):
                    tset.set(timer)
            timers_set.append(tset)
        network = self._init_network.copy()
        if self._ordered:
            flow_msg = np.asarray(packed["flow_msg"])
            flow_len = np.asarray(packed["flow_len"])
            _, psrc, pdst = self._pair_tables()
            for p in range(self._P):
                src, dst = Id(int(psrc[p])), Id(int(pdst[p]))
                for i in range(int(flow_len[p])):
                    network.send(
                        Envelope(src=src, dst=dst, msg=codec.unpack_msg(flow_msg[p, i]))
                    )
        else:
            cnt = np.asarray(packed["net_cnt"])
            src = np.asarray(packed["net_src"])
            dst = np.asarray(packed["net_dst"])
            msg = np.asarray(packed["net_msg"])
            for slot in range(self._E):
                if int(cnt[slot]):
                    env = Envelope(
                        src=Id(int(src[slot])),
                        dst=Id(int(dst[slot])),
                        msg=codec.unpack_msg(msg[slot]),
                    )
                    for _ in range(int(cnt[slot])):
                        network.send(env)
        history = None
        if codec.history_width:
            history = codec.unpack_history(np.asarray(packed["hist"]))
        crashed = [False] * self._N
        if self._max_crashes:
            crashed = [bool(c) for c in np.asarray(packed["crashed"])]
        return ActorModelState(
            actor_states=actor_states,
            network=network,
            timers_set=timers_set,
            crashed=crashed,
            history=history,
        )

    def packed_init_states(self):
        import jax.numpy as jnp

        self._packed_check()
        packed = [self.pack_state(s) for s in self.init_states()]
        return {
            k: jnp.stack([np.asarray(p[k]) for p in packed])
            for k in packed[0]
        }

    # -- traceable transition ----------------------------------------------

    def packed_fingerprint_view(self, state):
        """The fingerprintable view of a packed system state:

        - crash flags are excluded, mirroring the host state hash
          (reference ``src/actor/model_state.rs:86-97``);
        - the unordered envelope table is reduced to an order-insensitive
          multiset digest (``ops.fingerprint.multiset_digest``), so equal
          envelope multisets fingerprint identically WITHOUT the table
          being kept sorted — transitions and symmetry permutations never
          pay a per-candidate sort. Ordered flows are positionally
          canonical (head at slot 0) and hash as-is.
        """
        import jax.numpy as jnp

        from ..ops.fingerprint import multiset_digest

        out = {k: v for k, v in state.items() if k != "crashed"}
        if not self._ordered:
            src = out.pop("net_src")
            dst = out.pop("net_dst")
            msg = out.pop("net_msg")
            cnt = out.pop("net_cnt")
            rows = jnp.concatenate(
                [src[:, None], dst[:, None], msg, cnt[:, None]], axis=1
            ).astype(jnp.uint32)
            out["net_digest"] = multiset_digest(rows, cnt > 0)
        return out

    def packed_component_pairs(self, state):
        """Component-hash pairs of one packed state: ``(his, los)``, each
        ``(C,)`` uint32, one pair per component in a fixed order —

        - actor components ``0..N-1``: actor row ‖ timer word (crash flags
          excluded, like the view);
        - ordered nets: flow components ``N..N+P-1``: FIFO queue ‖ length;
          unordered nets: one component ``N``: the order-insensitive
          multiset digest of the envelope table;
        - history component last, when the codec carries one.

        Tag-seeded (``ops.fingerprint.component_seeds``) so the scheme is
        positional across components. ``packed_fingerprint`` chains these
        pairs; ``packed_expand_fps`` rehashes only the components a
        transition touches and reuses the parent's pairs for the rest —
        the algebraic identity that makes candidate fingerprints a
        delta-cost operation."""
        import jax.numpy as jnp

        from ..ops.fingerprint import hash_rows, multiset_digest

        N, P = self._N, self._P
        rows_t = jnp.concatenate(
            [state["rows"], state["timers"][:, None]], axis=1
        )
        his = [None]
        los = [None]
        his[0], los[0] = hash_rows(rows_t, jnp.arange(N, dtype=jnp.uint32))
        if self._ordered:
            Q, W = self._Q, self.codec.msg_width
            flow_t = jnp.concatenate(
                [
                    state["flow_msg"].reshape(P, Q * W),
                    state["flow_len"][:, None],
                ],
                axis=1,
            )
            fh, fl = hash_rows(
                flow_t, jnp.uint32(N) + jnp.arange(P, dtype=jnp.uint32)
            )
            net_comps = P
        else:
            rows = jnp.concatenate(
                [
                    state["net_src"][:, None],
                    state["net_dst"][:, None],
                    state["net_msg"],
                    state["net_cnt"][:, None],
                ],
                axis=1,
            ).astype(jnp.uint32)
            digest = multiset_digest(rows, state["net_cnt"] > 0)
            fh, fl = hash_rows(digest[None, :], jnp.asarray([N], jnp.uint32))
            net_comps = 1
        his.append(fh)
        los.append(fl)
        if self.codec.history_width:
            tag = jnp.asarray([N + net_comps], jnp.uint32)
            hh, hl = hash_rows(state["hist"][None, :], tag)
            his.append(hh)
            los.append(hl)
        return jnp.concatenate(his), jnp.concatenate(los)

    def packed_fingerprint(self, state):
        """Component-hash fingerprint (see ``packed_component_pairs``).
        Replaces the word-serial murmur over the fingerprint view: same
        view semantics (crash-excluded, net-order-insensitive), but
        per-candidate recomputation touches only changed components."""
        from ..ops.fingerprint import combine_pairs

        self._packed_check()
        return combine_pairs(*self.packed_component_pairs(state))

    # -- symmetry (orbit-proper; see core/batch.py) ------------------------

    def packed_symmetry(self):
        from ..core.batch import permutation_tables

        if self.codec.history_width:
            raise NotImplementedError(
                "symmetry with packed auxiliary history is unsupported "
                "(histories carry client identities that are not "
                "interchangeable)"
            )
        if self.flow_pairs is not None:
            raise NotImplementedError(
                "full-group symmetry with restricted flow_pairs is "
                "unsupported (the pair set is generally not closed under "
                "actor permutations)"
            )
        return permutation_tables(self._N)

    def packed_apply_permutation(self, state, new_to_old, old_to_new):
        """The symmetry group action on a packed system state: gather
        actor-indexed arrays by ``new_to_old`` and rewrite embedded ids via
        the codec hooks (device analog of the host
        ``ActorModelState._permuted``). The envelope table needs no re-sort:
        the fingerprint view digests it order-insensitively."""
        import jax
        import jax.numpy as jnp

        codec = self.codec
        n = self._N
        rows = state["rows"][new_to_old]
        rows = jax.vmap(
            lambda r: codec.rewrite_actor_row(self, r, old_to_new)
        )(rows)
        out = dict(state, rows=rows, timers=state["timers"][new_to_old])
        if "crashed" in state:
            out["crashed"] = state["crashed"][new_to_old]
        if self._ordered:
            if self.flow_pairs is not None:
                # Unreachable through the checkers (packed_symmetry
                # raises first); direct callers get the same message.
                raise NotImplementedError(
                    "permutation action with restricted flow_pairs is "
                    "unsupported"
                )
            # Flow (a, b) of the permuted state held flow
            # (new_to_old[a], new_to_old[b]) originally; queue order is
            # preserved, so the gathered table stays positionally canonical.
            flow = (new_to_old[:, None] * n + new_to_old[None, :]).reshape(-1)
            fmsg = state["flow_msg"][flow]
            fmsg = jax.vmap(
                jax.vmap(lambda v: codec.rewrite_msg_ids(self, v, old_to_new))
            )(fmsg)
            flen = state["flow_len"][flow]
            # Re-zero queue padding so id rewrites of dead slots cannot
            # perturb the canonical array.
            slot = jnp.arange(fmsg.shape[1])
            fmsg = jnp.where(
                slot[None, :, None] < flen[:, None, None], fmsg, jnp.uint32(0)
            )
            out.update(flow_msg=fmsg, flow_len=flen)
        else:
            cnt = state["net_cnt"]
            occ = cnt > 0
            o2n = old_to_new.astype(jnp.uint32)
            src = jnp.where(occ, o2n[state["net_src"]], jnp.uint32(0))
            dst = jnp.where(occ, o2n[state["net_dst"]], jnp.uint32(0))
            msg = jax.vmap(
                lambda v: codec.rewrite_msg_ids(self, v, old_to_new)
            )(state["net_msg"])
            msg = jnp.where(occ[:, None], msg, jnp.uint32(0))
            out.update(net_src=src, net_dst=dst, net_msg=msg)
            # No re-sort needed: the fingerprint view digests the envelope
            # table order-insensitively.
        return out

    def packed_refine_colors(self, state, colors):
        """Generic equivariant WL round for packed actor systems (see
        ``core/batch.py``): each actor's new color hashes its own row (with
        embedded ids replaced by their colors, reusing the codec's
        ``rewrite_actor_row``/``rewrite_msg_ids`` relabeling hooks — which
        must therefore be value-wise and shift-safe for arbitrary uint32
        "names", not just true permutations), its timer bits, and
        commutative digests of its incoming/outgoing envelopes tagged with
        the peer's color. ``crashed`` is EXCLUDED, matching
        ``packed_fingerprint_view`` — the dedup key the colors steer hashes
        the view, so including crash flags could split view-equal states
        into different canonical permutations."""
        import jax
        import jax.numpy as jnp

        from ..ops.fingerprint import avalanche32

        codec = self.codec
        n = self._N
        u = jnp.uint32

        def rows_under(c):
            return jax.vmap(
                lambda r: codec.rewrite_actor_row(self, r, c)
            )(state["rows"])

        rows_c = rows_under(colors)
        acc = colors * u(0x9E3779B1) + u(0x7F4A7C15)
        for j in range(rows_c.shape[1]):
            acc = acc * u(0x01000193) ^ rows_c[:, j]
        acc = avalanche32(acc * u(0x01000193) ^ state["timers"].astype(u))

        # Reverse row-references: envelopes flow colors both ways below,
        # but a row embedding actor j's id (votedFor, vote bitmaps, ...)
        # informs only the REFERRER's color — actor j must also learn who
        # references it or WL leaves non-automorphic actors tied (and
        # every such tie pays the n! fallback). References are detected
        # generically and exactly: rewrites gather by INDEX, so perturbing
        # slot j's name changes exactly the rows that reference j.
        hcol = avalanche32(colors * u(0x27D4EB2F) + u(0x165667B1))

        def rev_body(j, rev):
            cj = colors.at[j].set(colors[j] ^ u(0x80000001))
            refs = (rows_under(cj) != rows_c).any(axis=1)
            return rev.at[j].set(jnp.where(refs, hcol, u(0)).sum(dtype=u))

        rev = jax.lax.fori_loop(0, n, rev_body, jnp.zeros((n,), u))
        acc = avalanche32(acc ^ rev * u(0x9E3779B7))

        if self._ordered:
            P, Q = self._P, self._Q
            fmsg_c = jax.vmap(
                jax.vmap(lambda v: codec.rewrite_msg_ids(self, v, colors))
            )(state["flow_msg"])
            flen = state["flow_len"].astype(u)
            live = jnp.arange(Q, dtype=u)[None, :] < flen[:, None]
            h = jnp.full((P,), 0x811C9DC5, u)
            for q in range(Q):
                hq = h
                for w in range(fmsg_c.shape[2]):
                    hq = hq * u(0x01000193) ^ fmsg_c[:, q, w]
                h = jnp.where(live[:, q], hq, h)
            h = avalanche32(h ^ flen * u(0x9E3779B9))
            _, psrc, pdst = self._pair_tables()
            a = jnp.asarray(psrc)
            b = jnp.asarray(pdst)
            out_c = avalanche32(h ^ colors[b] * u(0xCC9E2D51) + u(0x52DCE729))
            in_c = avalanche32(h ^ colors[a] * u(0x1B873593) + u(0x38495AB5))
            out_sum = jax.ops.segment_sum(out_c, a, num_segments=n)
            in_sum = jax.ops.segment_sum(in_c, b, num_segments=n)
        else:
            msg_c = jax.vmap(
                lambda v: codec.rewrite_msg_ids(self, v, colors)
            )(state["net_msg"])
            cnt = state["net_cnt"].astype(u)
            occ = cnt > 0
            h = jnp.full((cnt.shape[0],), 0x811C9DC5, u)
            for w in range(msg_c.shape[1]):
                h = h * u(0x01000193) ^ msg_c[:, w]
            h = avalanche32(h ^ cnt * u(0x9E3779B9))
            src = state["net_src"].astype(jnp.int32)
            dst = state["net_dst"].astype(jnp.int32)
            out_c = jnp.where(
                occ,
                avalanche32(h ^ colors[dst] * u(0xCC9E2D51) + u(0x52DCE729)),
                u(0),
            )
            in_c = jnp.where(
                occ,
                avalanche32(h ^ colors[src] * u(0x1B873593) + u(0x38495AB5)),
                u(0),
            )
            out_sum = jax.ops.segment_sum(out_c, src, num_segments=n)
            in_sum = jax.ops.segment_sum(in_c, dst, num_segments=n)
        return avalanche32(
            acc ^ out_sum * u(0x85EBCA6B) ^ in_sum * u(0xC2B2AE35)
        )

    def _net_send(self, state, src, dst, msg, active):
        """One network send (host ``Network.send``): duplicating nets dedup,
        non-duplicating nets count, ordered nets append to the (src, dst)
        FIFO. Returns (state, overflow)."""
        import jax.numpy as jnp

        if self._ordered:
            Q = self._Q
            lookup, _, _ = self._pair_tables()
            full = src.astype(jnp.int32) * self._N + dst.astype(jnp.int32)
            p = jnp.asarray(lookup)[
                jnp.clip(full, 0, self._N * self._N - 1)
            ]
            # Excluded pairs behave as zero-capacity flows: the send
            # overflows and the transition is pruned (boundary semantics).
            allowed = p >= 0
            p = jnp.clip(p, 0, self._P - 1)
            length = state["flow_len"][p]
            ok = active & allowed & (length < Q)
            at = jnp.clip(length, 0, Q - 1).astype(jnp.int32)
            state = dict(state)
            state["flow_msg"] = state["flow_msg"].at[p, at].set(
                jnp.where(ok, msg, state["flow_msg"][p, at])
            )
            state["flow_len"] = state["flow_len"].at[p].add(
                jnp.where(ok, jnp.uint32(1), jnp.uint32(0))
            )
            return state, active & (~allowed | (length >= Q))

        src = src.astype(jnp.uint32)
        dst = dst.astype(jnp.uint32)
        cnt = state["net_cnt"]
        match = (
            (state["net_src"] == src)
            & (state["net_dst"] == dst)
            & (state["net_msg"] == msg[None, :]).all(axis=1)
            & (cnt > 0)
        )
        exists = match.any()
        first_match = jnp.argmax(match)
        empty = cnt == 0
        has_empty = empty.any()
        claim = jnp.argmax(empty)

        slot = jnp.where(exists, first_match, claim)
        ok = active & (exists | has_empty)
        if self._dup:
            add = jnp.where(exists, jnp.uint32(0), jnp.uint32(1))
        else:
            add = jnp.uint32(1)
        new_cnt = cnt.at[slot].add(jnp.where(ok, add, jnp.uint32(0)))
        write = ok & ~exists
        state = dict(state)
        state["net_src"] = state["net_src"].at[slot].set(
            jnp.where(write, src, state["net_src"][slot])
        )
        state["net_dst"] = state["net_dst"].at[slot].set(
            jnp.where(write, dst, state["net_dst"][slot])
        )
        state["net_msg"] = state["net_msg"].at[slot].set(
            jnp.where(write, msg, state["net_msg"][slot])
        )
        state["net_cnt"] = new_cnt
        overflow = active & ~exists & ~has_empty
        return state, overflow

    def _apply_callback(self, state, actor, row_new, sends, set_bits, cancel_bits, fired_bit=None):
        """Applies a callback's effects: row write, timer bookkeeping
        (fired timer cleared first, then sets, then cancels — matching the
        host's sequential command processing for set-then-cancel), sends.
        Returns (state, overflow)."""
        import jax.numpy as jnp

        state = dict(state)
        state["rows"] = state["rows"].at[actor].set(row_new)
        t = state["timers"][actor]
        if fired_bit is not None:
            t = t & ~(jnp.uint32(1) << fired_bit.astype(jnp.uint32))
        t = (t | set_bits) & ~cancel_bits
        state["timers"] = state["timers"].at[actor].set(t)
        overflow = jnp.bool_(False)
        for s in range(self.codec.send_capacity):
            dst = sends[s, 0]
            msg = sends[s, 1:]
            active = dst != jnp.uint32(self.codec.SEND_NONE)
            state, ov = self._net_send(
                state, state_src(actor), dst, msg, active
            )
            if self.codec.history_width:
                # Host: each Send runs record_msg_out on the running history
                # (sequential command processing, ``model.py:163-172``).
                hist_new = self.codec.history_on_send(
                    self, state["hist"], state_src(actor), dst, msg
                )
                state["hist"] = jnp.where(active, hist_new, state["hist"])
            overflow = overflow | ov
        return state, overflow

    def packed_step(self, state, action_id):
        import jax
        import jax.numpy as jnp

        self._packed_check()
        codec = self.codec
        N, E, T, W = self._N, self._E, self._T, codec.msg_width
        lossy = self._lossy_network
        aid = action_id.astype(jnp.int32)
        msg_branches = codec.on_msg_branches(self)
        timeout_branches = codec.on_timeout_branches(self)
        if not timeout_branches:
            # Timer-free codec: lax.cond still traces the timeout arm, so
            # substitute an inert branch (never selected — T == 0 means no
            # timeout action ids exist).
            def _inert(actor, row, bit):
                z = jnp.uint32(0)
                return (
                    row,
                    jnp.full(
                        (codec.send_capacity, 1 + codec.msg_width),
                        codec.SEND_NONE,
                    ),
                    z,
                    z,
                    jnp.bool_(False),
                )

            timeout_branches = [_inert] * max(1, len(msg_branches))
        type_ids = [
            codec.actor_type_id(i, a) for i, a in enumerate(self.actors_list)
        ]
        type_arr = jnp.asarray(type_ids, jnp.int32)

        ordered = self._ordered
        crashes = bool(self._max_crashes)
        deliver_ids = self._P if ordered else E
        drop_ids = deliver_ids if lossy else 0
        timeout_ids = N * T
        is_deliver = aid < deliver_ids
        is_drop = lossy & (aid >= deliver_ids) & (aid < deliver_ids + drop_ids)
        is_timeout = (aid >= deliver_ids + drop_ids) & (
            aid < deliver_ids + drop_ids + timeout_ids
        )
        is_crash = crashes & (aid >= deliver_ids + drop_ids + timeout_ids)

        slot = jnp.clip(
            jnp.where(is_drop, aid - deliver_ids, aid), 0, deliver_ids - 1
        )
        # T == 0 (timer-free systems): no timeout action ids exist, so
        # is_timeout is always false; T1 only keeps the index math traceable.
        T1 = max(T, 1)
        tk = jnp.clip(aid - deliver_ids - drop_ids, 0, N * T1 - 1)
        t_actor = tk // T1
        t_bit = (tk % T1).astype(jnp.uint32)
        crash_actor = jnp.clip(
            aid - deliver_ids - drop_ids - timeout_ids, 0, N - 1
        )

        if ordered:
            flow_len = state["flow_len"]
            present = flow_len[slot] > 0
            _, psrc, pdst = self._pair_tables()
            env_src = jnp.asarray(psrc)[slot]
            env_dst = jnp.asarray(pdst)[slot]
            env_msg = state["flow_msg"][slot, 0]
            cnt = None
        else:
            cnt = state["net_cnt"]
            present = cnt[slot] > 0
            env_src = state["net_src"][slot].astype(jnp.int32)
            env_dst = state["net_dst"][slot].astype(jnp.int32)
            env_msg = state["net_msg"][slot]
        dst_ok = env_dst < N
        if crashes:
            dst_crashed = state["crashed"][jnp.clip(env_dst, 0, N - 1)] == 1
        else:
            dst_crashed = jnp.bool_(False)

        # Which actor's callback runs (clamped for safety; masked by valid).
        actor = jnp.clip(jnp.where(is_timeout, t_actor, env_dst), 0, N - 1)
        row = state["rows"][actor]

        def run_msg(args):
            row, actor, src, msg, bit = args
            return jax.lax.switch(
                type_arr[actor],
                [
                    (lambda r, a, s, m, fn=fn: fn(a, r, s, m))
                    for fn in msg_branches
                ],
                row,
                actor,
                src,
                msg,
            )

        def run_timeout(args):
            row, actor, src, msg, bit = args
            return jax.lax.switch(
                type_arr[actor],
                [
                    (lambda r, a, b, fn=fn: fn(a, r, b))
                    for fn in timeout_branches
                ],
                row,
                actor,
                bit,
            )

        row_new, sends, set_bits, cancel_bits, changed = jax.lax.cond(
            is_timeout,
            run_timeout,
            run_msg,
            (row, actor, env_src, env_msg, t_bit),
        )

        no_sends = (sends[:, 0] == codec.SEND_NONE).all()
        no_bits_cmds = (set_bits == 0) & (cancel_bits == 0)
        is_no_op = ~changed & no_sends & no_bits_cmds
        # Host is_no_op_with_timer: unchanged + exactly a renewal of the
        # fired timer.
        renews_only = (
            ~changed
            & no_sends
            & (cancel_bits == 0)
            & (set_bits == (jnp.uint32(1) << t_bit))
        )

        timer_set = (
            (state["timers"][t_actor] >> t_bit) & jnp.uint32(1)
        ) == 1
        # Ordered networks must consume no-op deliveries to preserve FIFO
        # state (host ``model.py:246-249``); unordered prunes them.
        deliver_effective = (
            jnp.bool_(True) if ordered else ~is_no_op
        )
        valid_deliver = (
            is_deliver & present & dst_ok & ~dst_crashed & deliver_effective
        )
        valid_drop = is_drop & present
        valid_timeout = is_timeout & timer_set & ~renews_only
        if crashes:
            crash_count = state["crashed"].sum()
            valid_crash = (
                is_crash
                & (crash_count < jnp.uint32(self._max_crashes))
                & (state["crashed"][crash_actor] == 0)
            )
        else:
            valid_crash = jnp.bool_(False)

        # -- build each outcome and select ----------------------------------

        def consume_head(st):
            """Removes the head of ordered flow ``slot`` (shift keeps the
            queue canonical: head always at index 0)."""
            st = dict(st)
            q = st["flow_msg"][slot]
            shifted = jnp.concatenate(
                [q[1:], jnp.zeros((1, W), jnp.uint32)], axis=0
            )
            st["flow_msg"] = st["flow_msg"].at[slot].set(shifted)
            st["flow_len"] = st["flow_len"].at[slot].add(
                jnp.uint32(0) - 1
            )
            return st

        # Drop: duplicating removes the envelope entirely; counting nets
        # decrement; ordered removes the flow head (host Network.on_drop).
        if ordered:
            drop_state = consume_head(state)
        else:
            drop_state = dict(state)
            if self._dup:
                drop_state["net_cnt"] = cnt.at[slot].set(jnp.uint32(0))
            else:
                drop_state["net_cnt"] = cnt.at[slot].add(jnp.uint32(0) - 1)

        # Deliver: network effect first (host: on_deliver before
        # process_commands), then callback effects. The record_msg_in analog
        # applies to the PRE-send history (host: ``model.py:250-262``).
        deliver_state = dict(state)
        if codec.history_width:
            deliver_state["hist"] = codec.history_on_deliver(
                self, state["hist"], env_src, env_dst, env_msg
            )
        if ordered:
            deliver_state = consume_head(deliver_state)
        elif not self._dup:
            deliver_state["net_cnt"] = cnt.at[slot].add(jnp.uint32(0) - 1)
        # Ordered no-op deliveries consume the message but apply no other
        # effect (the host skips the callback result entirely).
        row_eff = jnp.where(is_no_op, state["rows"][actor], row_new)
        no_send_buf = jnp.full_like(sends, codec.SEND_NONE)
        sends_eff = jnp.where(is_no_op, no_send_buf, sends)
        set_eff = jnp.where(is_no_op, jnp.uint32(0), set_bits)
        cancel_eff = jnp.where(is_no_op, jnp.uint32(0), cancel_bits)
        deliver_state, ov_d = self._apply_callback(
            deliver_state, actor, row_eff, sends_eff, set_eff, cancel_eff
        )

        timeout_state, ov_t = self._apply_callback(
            dict(state), actor, row_new, sends, set_bits, cancel_bits,
            fired_bit=t_bit,
        )

        if crashes:
            crash_state = dict(state)
            crash_state["crashed"] = state["crashed"].at[crash_actor].set(
                jnp.uint32(1)
            )
            crash_state["timers"] = state["timers"].at[crash_actor].set(
                jnp.uint32(0)
            )

        overflow = (valid_deliver & ov_d) | (valid_timeout & ov_t)

        def pick(a, b, cond):
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(cond, x, y), a, b
            )

        out = pick(drop_state, deliver_state, is_drop)
        out = pick(timeout_state, out, is_timeout)
        if crashes:
            out = pick(crash_state, out, is_crash)
        valid = (
            valid_deliver | valid_drop | valid_timeout | valid_crash
        ) & ~overflow
        return out, valid

    def packed_expand(self, state):
        """Per-class expansion fast path (see ``BatchableModel``): builds
        the deliver / drop / timeout / crash candidate blocks separately,
        in ``packed_step``'s action-id order, so each class pays only its
        own work. ``packed_step`` (kept as the single-action path for the
        TPU simulation checker, and as the oracle for
        ``tests/test_packed_expand.py``) materializes all four outcome
        variants and runs BOTH callback switches per candidate — under
        vmap every lane executes every branch — which dominated wave time
        on action-heavy models (raft-5: expand was 92% of the wave; drop
        candidates here cost one FIFO/count update instead of two full
        callback traces + four state builds)."""
        import jax

        s = self._class_steps(state)
        return self._expand_from_steps(s)

    def _expand_from_steps(self, s):
        import jax
        import jax.numpy as jnp

        slots = jnp.arange(s["D"], dtype=jnp.int32)
        parts = [jax.vmap(s["deliver"])(slots)]
        if self._lossy_network:
            parts.append(jax.vmap(s["drop"])(slots))
        if s["T"]:
            parts.append(
                jax.vmap(s["timeout"])(
                    jnp.arange(self._N * s["T"], dtype=jnp.int32)
                )
            )
        if s["crashes"]:
            parts.append(
                jax.vmap(s["crash"])(jnp.arange(self._N, dtype=jnp.int32))
            )
        cand = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *[p[0] for p in parts]
        )
        valid = jnp.concatenate([p[1] for p in parts])
        return cand, valid

    def _class_steps(self, state):
        """The four per-class child builders (deliver/drop/timeout/crash)
        as closures over ``state``, in ``packed_step``'s action-id order.
        Shared by ``packed_expand`` (vmapped per class) and
        ``packed_take`` (lax.switch for one action id)."""
        import jax
        import jax.numpy as jnp

        self._packed_check()
        codec = self.codec
        N, E, T, W = self._N, self._E, self._T, codec.msg_width
        ordered = self._ordered
        crashes = bool(self._max_crashes)
        msg_branches = codec.on_msg_branches(self)
        timeout_branches = codec.on_timeout_branches(self)
        type_arr = jnp.asarray(
            [codec.actor_type_id(i, a) for i, a in enumerate(self.actors_list)],
            jnp.int32,
        )
        D = self._P if ordered else E

        def env_at(slot):
            """(present, src, dst, msg) of deliver/drop slot ``slot``."""
            if ordered:
                _, psrc, pdst = self._pair_tables()
                return (
                    state["flow_len"][slot] > 0,
                    jnp.asarray(psrc)[slot],
                    jnp.asarray(pdst)[slot],
                    state["flow_msg"][slot, 0],
                )
            return (
                state["net_cnt"][slot] > 0,
                state["net_src"][slot].astype(jnp.int32),
                state["net_dst"][slot].astype(jnp.int32),
                state["net_msg"][slot],
            )

        def consume(st, slot):
            """Removes slot's message: FIFO head shift / count decrement
            (identical to packed_step's consume_head / decrement)."""
            st = dict(st)
            if ordered:
                q = st["flow_msg"][slot]
                shifted = jnp.concatenate(
                    [q[1:], jnp.zeros((1, W), jnp.uint32)], axis=0
                )
                st["flow_msg"] = st["flow_msg"].at[slot].set(shifted)
                st["flow_len"] = st["flow_len"].at[slot].add(jnp.uint32(0) - 1)
            else:
                st["net_cnt"] = st["net_cnt"].at[slot].add(jnp.uint32(0) - 1)
            return st

        def crashed_at(dst):
            if crashes:
                return state["crashed"][jnp.clip(dst, 0, N - 1)] == 1
            return jnp.bool_(False)

        def no_op_of(changed, sends, set_bits, cancel_bits):
            no_sends = (sends[:, 0] == codec.SEND_NONE).all()
            return ~changed & no_sends & (set_bits == 0) & (cancel_bits == 0)

        def step_deliver(slot):
            present, env_src, env_dst, env_msg = env_at(slot)
            actor = jnp.clip(env_dst, 0, N - 1)
            row = state["rows"][actor]
            row_new, sends, set_bits, cancel_bits, changed = jax.lax.switch(
                type_arr[actor],
                [
                    (lambda r, a, s, m, fn=fn: fn(a, r, s, m))
                    for fn in msg_branches
                ],
                row,
                actor,
                env_src,
                env_msg,
            )
            is_no_op = no_op_of(changed, sends, set_bits, cancel_bits)
            out = dict(state)
            if codec.history_width:
                out["hist"] = codec.history_on_deliver(
                    self, state["hist"], env_src, env_dst, env_msg
                )
            if ordered or not self._dup:
                out = consume(out, slot)
            # Ordered no-op deliveries consume the message but apply no
            # other effect (host skips the callback result entirely).
            row_eff = jnp.where(is_no_op, row, row_new)
            sends_eff = jnp.where(
                is_no_op, jnp.full_like(sends, codec.SEND_NONE), sends
            )
            set_eff = jnp.where(is_no_op, jnp.uint32(0), set_bits)
            cancel_eff = jnp.where(is_no_op, jnp.uint32(0), cancel_bits)
            out, ov = self._apply_callback(
                out, actor, row_eff, sends_eff, set_eff, cancel_eff
            )
            valid = (
                present
                & (env_dst < N)
                & ~crashed_at(env_dst)
                & (jnp.bool_(True) if ordered else ~is_no_op)
                & ~ov
            )
            return out, valid

        def step_drop(slot):
            present, _, _, _ = env_at(slot)
            out = dict(state)
            if ordered:
                out = consume(out, slot)
            elif self._dup:
                out["net_cnt"] = state["net_cnt"].at[slot].set(jnp.uint32(0))
            else:
                out = consume(out, slot)
            return out, present

        def step_timeout(k):
            t_actor = k // T
            t_bit = (k % T).astype(jnp.uint32)
            row = state["rows"][t_actor]
            row_new, sends, set_bits, cancel_bits, changed = jax.lax.switch(
                type_arr[t_actor],
                [
                    (lambda r, a, b, fn=fn: fn(a, r, b))
                    for fn in timeout_branches
                ],
                row,
                t_actor,
                t_bit,
            )
            renews_only = (
                ~changed
                & (sends[:, 0] == codec.SEND_NONE).all()
                & (cancel_bits == 0)
                & (set_bits == (jnp.uint32(1) << t_bit))
            )
            timer_set = (
                (state["timers"][t_actor] >> t_bit) & jnp.uint32(1)
            ) == 1
            out, ov = self._apply_callback(
                dict(state), t_actor, row_new, sends, set_bits, cancel_bits,
                fired_bit=t_bit,
            )
            return out, timer_set & ~renews_only & ~ov

        def step_crash(i):
            out = dict(state)
            out["crashed"] = state["crashed"].at[i].set(jnp.uint32(1))
            out["timers"] = state["timers"].at[i].set(jnp.uint32(0))
            valid = (state["crashed"].sum() < jnp.uint32(self._max_crashes)) & (
                state["crashed"][i] == 0
            )
            return out, valid

        return {
            "deliver": step_deliver,
            "drop": step_drop,
            "timeout": step_timeout,
            "crash": step_crash,
            "env_at": env_at,
            "consume": consume,
            "crashed_at": crashed_at,
            "no_op_of": no_op_of,
            "D": D,
            "T": T,
            "crashes": crashes,
            "type_arr": type_arr,
            "msg_branches": msg_branches,
            "timeout_branches": timeout_branches,
        }

    def packed_expand_fps_supported(self):
        """The fps wave checks boundaries per changed row; a codec that
        customizes ``packed_within_boundary`` must supply the per-row
        decomposition (``packed_row_within_boundary``) or the fps path
        would silently admit out-of-boundary children. Mismatched codecs
        fall back to the materializing wave."""
        codec_cls = type(self.codec)
        wb_custom = (
            codec_cls.packed_within_boundary
            is not ActorPackedCodec.packed_within_boundary
        )
        row_custom = (
            codec_cls.packed_row_within_boundary
            is not ActorPackedCodec.packed_row_within_boundary
        )
        return (not wb_custom) or row_custom

    def packed_expand_fps(self, state):
        """Fingerprints + validity of every child WITHOUT materializing
        them (``core/batch.py`` contract): candidate fingerprints are
        computed from the parent's component-hash pairs
        (``packed_component_pairs``) by rehashing only the components a
        transition touches — the changed actor row, the consumed/appended
        flow rows (ordered) or the algebraically-updated multiset digest
        (unordered), and the history vector. Per-candidate cost is the
        delta size plus the 2C-round combine chain; the F × A candidate
        grid never exists as state arrays, which is the byte diet VERDICT
        r04 #2 demanded (abd3o measured 29.5KB of HBM traffic per
        candidate on the materializing path). Replaces the reference's
        per-state hashing in its BFS hot loop
        (``/root/reference/src/checker/bfs.rs:275-315``)."""
        import jax
        import jax.numpy as jnp

        from ..ops.fingerprint import (
            acc_finalize,
            hash_rows,
            multiset_row_pairs,
            pairs_acc,
        )

        self._packed_check()
        codec = self.codec
        N, E, T, W = self._N, self._E, self._T, codec.msg_width
        ordered, dup = self._ordered, self._dup
        crashes = bool(self._max_crashes)
        S = codec.send_capacity
        P = self._P
        Q = self._Q if ordered else 0
        hist_w = codec.history_width
        net_comps = P if ordered else 1
        hist_tag = N + net_comps
        C = N + net_comps + (1 if hist_w else 0)
        K = 1 + S  # working-set slots: consumed row + one per send
        SN = jnp.uint32(codec.SEND_NONE)

        s = self._class_steps(state)
        env_at, no_op_of = s["env_at"], s["no_op_of"]
        crashed_at = s["crashed_at"]
        type_arr = s["type_arr"]
        msg_branches, timeout_branches = s["msg_branches"], s["timeout_branches"]
        D = s["D"]
        phis, plos = self.packed_component_pairs(state)
        parent_acc = pairs_acc(phis, plos)

        def actor_pair(actor, row, tmr):
            words = jnp.concatenate([row, tmr[None]])
            h, l = hash_rows(words[None, :], actor[None].astype(jnp.uint32))
            return h[0], l[0]

        def flow_pair(pid, q, ln):
            words = jnp.concatenate([q.reshape(Q * W), ln[None]])
            tag = (jnp.uint32(N) + pid.astype(jnp.uint32))[None]
            h, l = hash_rows(words[None, :], tag)
            return h[0], l[0]

        def net_pair(digest):
            h, l = hash_rows(digest[None, :], jnp.asarray([N], jnp.uint32))
            return h[0], l[0]

        def hist_pair(hist):
            tag = jnp.asarray([hist_tag], jnp.uint32)
            h, l = hash_rows(hist[None, :], tag)
            return h[0], l[0]

        def final_fp(subs):
            """Parent accumulator with per-candidate component
            substitutions (O(1) arithmetic each — the commutative combine
            is what makes candidate fps delta-cost). ``subs``:
            (comp_idx, hi, lo, enabled); the targeted components must be
            DISTINCT within one candidate (the working sets guarantee it),
            or a later delta would be computed against the parent's value
            instead of the earlier substitution's."""
            sum_hi, xor_hi = parent_acc[0], parent_acc[1]
            sum_lo, xor_lo = parent_acc[2], parent_acc[3]
            for ci, nh, nl, en in subs:
                oh = phis[ci]
                ol = plos[ci]
                dh = jnp.where(en, nh, oh)
                dl = jnp.where(en, nl, ol)
                sum_hi = sum_hi + dh - oh
                xor_hi = xor_hi ^ dh ^ oh
                sum_lo = sum_lo + dl - ol
                xor_lo = xor_lo ^ dl ^ ol
            return acc_finalize(
                jnp.stack([sum_hi, xor_hi, sum_lo, xor_lo]), C
            )

        def row_pair_of(row_words):
            h, l = multiset_row_pairs(row_words[None, :])
            return h[0], l[0]

        if not ordered:
            parent_digest = self.packed_fingerprint_view(state)["net_digest"]

            def env_row(src, dst, msg, cnt):
                return jnp.concatenate(
                    [
                        src.astype(jnp.uint32)[None],
                        dst.astype(jnp.uint32)[None],
                        msg.astype(jnp.uint32),
                        cnt[None],
                    ]
                )

            def digest_adjust(digest, src, dst, msg, old_cnt, new_cnt, en):
                """- old row contribution + new row contribution, exactly
                as ``multiset_digest`` folds active rows."""
                oh, ol = row_pair_of(env_row(src, dst, msg, old_cnt))
                nh, nl = row_pair_of(env_row(src, dst, msg, new_cnt))
                rm = en & (old_cnt > 0)
                ad = en & (new_cnt > 0)
                sum_hi, xor_hi, sum_lo, xor_lo = digest
                sum_hi = sum_hi - jnp.where(rm, oh, 0) + jnp.where(ad, nh, 0)
                xor_hi = xor_hi ^ jnp.where(rm, oh, 0) ^ jnp.where(ad, nh, 0)
                sum_lo = sum_lo - jnp.where(rm, ol, 0) + jnp.where(ad, nl, 0)
                xor_lo = xor_lo ^ jnp.where(rm, ol, 0) ^ jnp.where(ad, nl, 0)
                return jnp.stack([sum_hi, xor_hi, sum_lo, xor_lo])

        if ordered:
            lookup, _, _ = self._pair_tables()
            lookup_a = jnp.asarray(lookup)

            def flows_apply(init, sends, src):
                """Sequential send application on a K-entry working set of
                touched flow rows — mirrors ``_net_send``'s ordered branch
                (including overflow/excluded-pair pruning) without copying
                the flow table."""
                ids = jnp.full((K,), -1, jnp.int32)
                qs = jnp.zeros((K, Q, W), jnp.uint32)
                lns = jnp.zeros((K,), jnp.uint32)
                if init is not None:
                    slot, q0, ln0 = init
                    ids = ids.at[0].set(slot)
                    qs = qs.at[0].set(q0)
                    lns = lns.at[0].set(ln0)
                ov = jnp.bool_(False)
                for si in range(S):
                    dst = sends[si, 0]
                    msg = sends[si, 1:]
                    active = dst != SN
                    # Index expression kept IDENTICAL to _net_send's so the
                    # fingerprinted append and the materialized append can
                    # never diverge on out-of-range dst values.
                    full = src.astype(jnp.int32) * N + dst.astype(jnp.int32)
                    p = lookup_a[jnp.clip(full, 0, N * N - 1)]
                    allowed = p >= 0
                    p = jnp.clip(p, 0, P - 1)
                    match = ids == p
                    found = match.any()
                    j = jnp.where(
                        found, jnp.argmax(match), jnp.argmax(ids < 0)
                    )
                    base_q = jnp.where(found, qs[j], state["flow_msg"][p])
                    base_ln = jnp.where(found, lns[j], state["flow_len"][p])
                    ok = active & allowed & (base_ln < Q)
                    at = jnp.clip(base_ln, 0, Q - 1).astype(jnp.int32)
                    nq = base_q.at[at].set(jnp.where(ok, msg, base_q[at]))
                    nln = base_ln + jnp.where(ok, jnp.uint32(1), jnp.uint32(0))
                    touch = active & allowed
                    ids = ids.at[j].set(jnp.where(touch, p, ids[j]))
                    qs = qs.at[j].set(jnp.where(touch, nq, qs[j]))
                    lns = lns.at[j].set(jnp.where(touch, nln, lns[j]))
                    ov = ov | (active & (~allowed | (base_ln >= Q)))
                return ids, qs, lns, ov

            def flow_subs(ids, qs, lns):
                subs = []
                for j in range(K):
                    h, l = flow_pair(ids[j], qs[j], lns[j])
                    subs.append((N + ids[j], h, l, ids[j] >= 0))
                return subs

        else:

            def net_apply(digest, cons_slot, do_consume, sends, src):
                """Sequential send application on the multiset digest with
                a K-entry working set of touched (src, dst, msg) rows —
                mirrors ``_net_send``'s unordered branch: duplicating nets
                dedup, non-duplicating count, empty-slot availability
                tracked as a running count (the digest is slot-agnostic,
                so only the COUNT of empties matters for overflow)."""
                esrc = jnp.zeros((K,), jnp.uint32)
                edst = jnp.zeros((K,), jnp.uint32)
                emsg = jnp.zeros((K, W), jnp.uint32)
                eold = jnp.zeros((K,), jnp.uint32)
                enew = jnp.zeros((K,), jnp.uint32)
                eused = jnp.zeros((K,), bool)
                empties = (state["net_cnt"] == 0).sum(dtype=jnp.int32)
                if do_consume:
                    c0 = state["net_cnt"][cons_slot]
                    esrc = esrc.at[0].set(state["net_src"][cons_slot])
                    edst = edst.at[0].set(state["net_dst"][cons_slot])
                    emsg = emsg.at[0].set(state["net_msg"][cons_slot])
                    eold = eold.at[0].set(c0)
                    enew = enew.at[0].set(c0 - 1)
                    eused = eused.at[0].set(True)
                    empties = empties + (c0 == 1)
                ov = jnp.bool_(False)
                for si in range(S):
                    dst = sends[si, 0]
                    msg = sends[si, 1:]
                    active = dst != SN
                    src_u = src.astype(jnp.uint32)
                    wmatch = (
                        eused
                        & (esrc == src_u)
                        & (edst == dst)
                        & (emsg == msg[None, :]).all(axis=1)
                    )
                    wfound = wmatch.any()
                    wj = jnp.argmax(wmatch)
                    pmatch = (
                        (state["net_src"] == src_u)
                        & (state["net_dst"] == dst)
                        & (state["net_msg"] == msg[None, :]).all(axis=1)
                        & (state["net_cnt"] > 0)
                    )
                    pfound = pmatch.any()
                    pcnt = state["net_cnt"][jnp.argmax(pmatch)]
                    cur = jnp.where(
                        wfound, enew[wj], jnp.where(pfound, pcnt, 0)
                    )
                    old0 = jnp.where(pfound, pcnt, 0)  # first-touch old cnt
                    exists = cur > 0
                    has_empty = empties > 0
                    if dup:
                        add = jnp.where(exists, jnp.uint32(0), jnp.uint32(1))
                    else:
                        add = jnp.uint32(1)
                    ok = active & (exists | has_empty)
                    ncnt = cur + jnp.where(ok, add, jnp.uint32(0))
                    claim = ok & ~exists
                    j = jnp.where(wfound, wj, jnp.argmax(~eused))
                    touch = ok
                    esrc = esrc.at[j].set(jnp.where(touch, src_u, esrc[j]))
                    edst = edst.at[j].set(jnp.where(touch, dst, edst[j]))
                    emsg = emsg.at[j].set(jnp.where(touch, msg, emsg[j]))
                    eold = eold.at[j].set(
                        jnp.where(touch & ~wfound, old0, eold[j])
                    )
                    enew = enew.at[j].set(jnp.where(touch, ncnt, enew[j]))
                    eused = eused.at[j].set(eused[j] | touch)
                    empties = empties - claim
                    ov = ov | (active & ~exists & ~has_empty)
                for j in range(K):
                    digest = digest_adjust(
                        digest,
                        esrc[j],
                        edst[j],
                        emsg[j],
                        eold[j],
                        enew[j],
                        eused[j],
                    )
                return digest, ov

        def hist_after(hist, sends, src):
            if not hist_w:
                return hist
            for si in range(S):
                dst = sends[si, 0]
                msg = sends[si, 1:]
                active = dst != SN
                hn = codec.history_on_send(self, hist, src, dst, msg)
                hist = jnp.where(active, hn, hist)
            return hist

        def callback_effects(actor, branches, *args):
            row_new, sends, set_bits, cancel_bits, changed = jax.lax.switch(
                type_arr[actor], branches, *args
            )
            return row_new, sends, set_bits, cancel_bits, changed

        def fps_deliver(slot):
            present, env_src, env_dst, env_msg = env_at(slot)
            actor = jnp.clip(env_dst, 0, N - 1)
            row = state["rows"][actor]
            row_new, sends, set_bits, cancel_bits, changed = callback_effects(
                actor,
                [
                    (lambda r, a, sr, m, fn=fn: fn(a, r, sr, m))
                    for fn in msg_branches
                ],
                row,
                actor,
                env_src,
                env_msg,
            )
            is_no_op = no_op_of(changed, sends, set_bits, cancel_bits)
            row_eff = jnp.where(is_no_op, row, row_new)
            sends_eff = jnp.where(
                is_no_op, jnp.full_like(sends, codec.SEND_NONE), sends
            )
            set_eff = jnp.where(is_no_op, jnp.uint32(0), set_bits)
            cancel_eff = jnp.where(is_no_op, jnp.uint32(0), cancel_bits)
            t_new = (state["timers"][actor] | set_eff) & ~cancel_eff
            src = state_src(actor)
            ah, al = actor_pair(actor, row_eff, t_new)
            subs = [(actor.astype(jnp.int32), ah, al, jnp.bool_(True))]
            if ordered:
                q = state["flow_msg"][slot]
                shifted = jnp.concatenate(
                    [q[1:], jnp.zeros((1, W), jnp.uint32)], axis=0
                )
                ids, qs, lns, ov = flows_apply(
                    (slot, shifted, state["flow_len"][slot] - 1),
                    sends_eff,
                    src,
                )
                subs += flow_subs(ids, qs, lns)
            else:
                digest, ov = net_apply(
                    parent_digest, slot, not dup, sends_eff, src
                )
                dh, dl = net_pair(digest)
                subs.append((jnp.int32(N), dh, dl, jnp.bool_(True)))
            if hist_w:
                hist = codec.history_on_deliver(
                    self, state["hist"], env_src, env_dst, env_msg
                )
                hist = hist_after(hist, sends_eff, src)
                hh, hl = hist_pair(hist)
                subs.append((jnp.int32(hist_tag), hh, hl, jnp.bool_(True)))
            hi, lo = final_fp(subs)
            valid = (
                present
                & (env_dst < N)
                & ~crashed_at(env_dst)
                & (jnp.bool_(True) if ordered else ~is_no_op)
                & ~ov
                & codec.packed_row_within_boundary(self, row_eff)
            )
            return hi, lo, valid

        def fps_drop(slot):
            present, env_src, env_dst, env_msg = env_at(slot)
            if ordered:
                q = state["flow_msg"][slot]
                shifted = jnp.concatenate(
                    [q[1:], jnp.zeros((1, W), jnp.uint32)], axis=0
                )
                h, l = flow_pair(slot, shifted, state["flow_len"][slot] - 1)
                subs = [(N + slot, h, l, jnp.bool_(True))]
            else:
                c0 = state["net_cnt"][slot]
                new_cnt = jnp.uint32(0) if dup else c0 - 1
                digest = digest_adjust(
                    parent_digest,
                    state["net_src"][slot],
                    state["net_dst"][slot],
                    state["net_msg"][slot],
                    c0,
                    new_cnt,
                    jnp.bool_(True),
                )
                dh, dl = net_pair(digest)
                subs = [(jnp.int32(N), dh, dl, jnp.bool_(True))]
            return (*final_fp(subs), present)

        def fps_timeout(k):
            t_actor = k // T
            t_bit = (k % T).astype(jnp.uint32)
            row = state["rows"][t_actor]
            row_new, sends, set_bits, cancel_bits, changed = callback_effects(
                t_actor,
                [
                    (lambda r, a, b, fn=fn: fn(a, r, b))
                    for fn in timeout_branches
                ],
                row,
                t_actor,
                t_bit,
            )
            renews_only = (
                ~changed
                & (sends[:, 0] == codec.SEND_NONE).all()
                & (cancel_bits == 0)
                & (set_bits == (jnp.uint32(1) << t_bit))
            )
            timer_set = (
                (state["timers"][t_actor] >> t_bit) & jnp.uint32(1)
            ) == 1
            t = state["timers"][t_actor] & ~(
                jnp.uint32(1) << t_bit
            )
            t_new = (t | set_bits) & ~cancel_bits
            src = state_src(t_actor)
            ah, al = actor_pair(t_actor, row_new, t_new)
            subs = [(t_actor.astype(jnp.int32), ah, al, jnp.bool_(True))]
            if ordered:
                ids, qs, lns, ov = flows_apply(None, sends, src)
                subs += flow_subs(ids, qs, lns)
            else:
                digest, ov = net_apply(
                    parent_digest, jnp.int32(0), False, sends, src
                )
                dh, dl = net_pair(digest)
                subs.append((jnp.int32(N), dh, dl, jnp.bool_(True)))
            if hist_w:
                hist = hist_after(state["hist"], sends, src)
                hh, hl = hist_pair(hist)
                subs.append((jnp.int32(hist_tag), hh, hl, jnp.bool_(True)))
            hi, lo = final_fp(subs)
            valid = (
                timer_set
                & ~renews_only
                & ~ov
                & codec.packed_row_within_boundary(self, row_new)
            )
            return hi, lo, valid

        def fps_crash(i):
            ah, al = actor_pair(i, state["rows"][i], jnp.uint32(0))
            hi, lo = final_fp([(i.astype(jnp.int32), ah, al, jnp.bool_(True))])
            valid = (
                state["crashed"].sum() < jnp.uint32(self._max_crashes)
            ) & (state["crashed"][i] == 0)
            return hi, lo, valid

        slots = jnp.arange(D, dtype=jnp.int32)
        parts = [jax.vmap(fps_deliver)(slots)]
        if self._lossy_network:
            parts.append(jax.vmap(fps_drop)(slots))
        if T:
            parts.append(
                jax.vmap(fps_timeout)(jnp.arange(N * T, dtype=jnp.int32))
            )
        if crashes:
            parts.append(
                jax.vmap(fps_crash)(jnp.arange(N, dtype=jnp.int32))
            )
        hi = jnp.concatenate([p[0] for p in parts])
        lo = jnp.concatenate([p[1] for p in parts])
        valid = jnp.concatenate([p[2] for p in parts])
        return hi, lo, valid

    def packed_take(self, state, action_id):
        """Single-child materializer for the fps wave (``core/batch.py``):
        builds exactly ``packed_step``'s outcome for one action id using
        the per-class builders — no four-variant materialization. Under
        vmap a ``lax.switch`` runs every branch per lane, but this is
        called on the post-dedup *fresh* lanes only (a fraction of the
        F × A grid), so the all-branches cost is paid n_new times, not
        B times. Equivalence with ``packed_step`` is pinned by
        ``tests/test_expand_fps.py``."""
        import jax
        import jax.numpy as jnp

        s = self._class_steps(state)
        D, T = s["D"], s["T"]
        aid = jnp.asarray(action_id, jnp.int32)
        branches = [lambda o: s["deliver"](o)[0]]
        bounds = [D]
        if self._lossy_network:
            branches.append(lambda o: s["drop"](o - D)[0])
            bounds.append(2 * D)
        if T:
            off = bounds[-1]
            branches.append(lambda o, off=off: s["timeout"](o - off)[0])
            bounds.append(off + self._N * T)
        if s["crashes"]:
            off = bounds[-1]
            branches.append(lambda o, off=off: s["crash"](o - off)[0])
            bounds.append(off + self._N)
        cls = jnp.int32(0)
        for k in range(1, len(bounds)):
            cls = jnp.where(aid >= bounds[k - 1], jnp.int32(k), cls)
        out = jax.lax.switch(cls, branches, aid)
        # Normalize leaf dtypes/structure to the input's (builders always
        # return full dicts, so structure already matches).
        return {k: out[k] for k in state}

    def packed_conditions(self):
        self._packed_check()
        conds = self.codec.packed_conditions(self)
        # Codecs emit one condition per property *as originally added*;
        # ``retain_properties`` may have since narrowed the model, so select
        # by the recorded append positions.
        if len(conds) != self._properties_added:
            raise ValueError(
                "codec.packed_conditions must align with the model's "
                f"properties as added: {len(conds)} != {self._properties_added}"
            )
        return [conds[i] for i in self._property_codec_pos]

    def packed_within_boundary(self, state):
        return self.codec.packed_within_boundary(self, state)


def state_src(actor):
    """The sender id for commands emitted by ``actor`` (host: commands are
    processed with ``src = the acting actor``)."""
    import jax.numpy as jnp

    return actor.astype(jnp.int32)
