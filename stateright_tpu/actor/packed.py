"""Packed actor systems: staging ``ActorModel`` transitions onto the TPU.

The host ``ActorModel`` (``stateright_tpu.actor.model``) enumerates
data-dependent action sets and runs arbitrary Python actor callbacks — the
reference's design (``/root/reference/src/actor/model.rs:214-649``), which
cannot be traced. This module provides the fixed-width staged equivalent
(SURVEY §2.2 names ``ActorModel`` "the prime candidate for the fixed-width
staged transition function"):

- **actor rows**: per-actor state packs into a ``(N, R)`` u32 matrix;
- **network table**: a bounded ``(E,)``-slot envelope table (src, dst,
  msg words, count) kept *canonically sorted* so identical envelope
  multisets produce identical arrays (the host hashes networks
  order-insensitively; sorting is the device analog);
- **timers**: one bitmask word per actor;
- **dense actions**: ``E`` Deliver ids + ``E`` Drop ids (lossy only) +
  ``N×T`` Timeout ids, each with a traceable guard;
- **actor callbacks**: each actor type supplies jax-traceable
  ``on_msg``/``on_timeout`` kernels via an ``ActorPackedCodec``;
  heterogeneous systems dispatch with ``lax.switch``.

Parity-scoped v1 (each limit raises loudly, host checkers remain available
for the rest): unordered networks only (ordered FIFO flows need ring
buffers), no auxiliary history (``LinearizabilityTester`` histories are
host-only by design — SURVEY §7 hard parts), and no crash faults (the host
state hash deliberately excludes ``crashed``, which device fingerprints
cannot reproduce without aliasing distinct live states).

The transition semantics mirror the host model exactly — no-op pruning
(``is_no_op``/``is_no_op_with_timer``), deliver-before-send network
effects, fired-timer clearing before command processing — so packed and
host checkers agree on exact state counts (the parity test contract).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ..core.batch import BatchableModel
from .actor import Id, Out
from .model import ActorModel
from .model_state import ActorModelState
from .network import (
    Envelope,
    Network,
    ORDERED,
    UNORDERED_DUPLICATING,
    UNORDERED_NONDUPLICATING,
)
from .timers import Timers


class ActorPackedCodec:
    """Model-specific packing contract consumed by ``PackedActorModel``.

    Widths are static; the traceable kernels receive/return u32 arrays:

    - ``on_msg`` branch (one per actor type):
      ``fn(id, row, src, msg) -> (row', sends, set_bits, cancel_bits, changed)``
      with ``id``/``src`` scalar i32, ``row`` ``(R,)`` u32, ``msg`` ``(W,)``
      u32, ``sends`` ``(S, 1+W)`` u32 (column 0 = destination id, or
      ``SEND_NONE`` for unused rows), timer masks scalar u32, ``changed``
      scalar bool (the analog of returning a new state vs ``None``).
    - ``on_timeout`` branch: ``fn(id, row, timer_id) -> same``.
    """

    SEND_NONE = np.uint32(0xFFFFFFFF)

    msg_width: int
    state_width: int
    timer_values: List[Any]  # timer value -> bit index by position
    send_capacity: int

    # -- host <-> packed conversions --------------------------------------

    def pack_actor_state(self, actor_index: int, state) -> np.ndarray:
        raise NotImplementedError

    def unpack_actor_state(self, actor_index: int, row: np.ndarray):
        raise NotImplementedError

    def pack_msg(self, msg) -> np.ndarray:
        raise NotImplementedError

    def unpack_msg(self, vec: np.ndarray):
        raise NotImplementedError

    # -- traceable kernels -------------------------------------------------

    def actor_type_id(self, actor_index: int, actor) -> int:
        return 0

    def on_msg_branches(self, model) -> List[Callable]:
        raise NotImplementedError

    def on_timeout_branches(self, model) -> List[Callable]:
        raise NotImplementedError

    # -- traceable model hooks ---------------------------------------------

    def packed_conditions(self, model) -> List[Callable]:
        raise NotImplementedError

    def packed_within_boundary(self, model, state) -> Any:
        import jax.numpy as jnp

        return jnp.bool_(True)


class PackedActorModel(ActorModel, BatchableModel):
    """An ``ActorModel`` that additionally implements the packed protocol.

    Build it exactly like an ``ActorModel`` (``.actor()``,
    ``.init_network()``, ``.property()``, …) and attach a codec; the packed
    side is validated lazily on first use so host-only checking of
    unsupported configurations still works.
    """

    def __init__(self, codec: ActorPackedCodec, cfg=None, init_history=None):
        super().__init__(cfg=cfg, init_history=init_history)
        self.codec = codec
        self.envelope_capacity = 32

    def with_envelope_capacity(self, capacity: int) -> "PackedActorModel":
        """Sets the network table's slot count. Must upper-bound the
        reachable distinct-envelope count: overflowing transitions are
        pruned, which the exact-count parity tests surface as a mismatch."""
        self.envelope_capacity = capacity
        return self

    # -- validation --------------------------------------------------------

    def _packed_check(self):
        if self.init_history is not None:
            raise NotImplementedError(
                "packed actor systems do not support auxiliary history "
                "(consistency-tester properties evaluate on the host path)"
            )
        if self._max_crashes:
            raise NotImplementedError(
                "packed actor systems do not support crash faults (the host "
                "state hash excludes `crashed`, which device fingerprints "
                "cannot mirror)"
            )
        if self._init_network.kind == ORDERED:
            raise NotImplementedError(
                "packed actor systems support unordered networks only"
            )
        if len(self._init_network.data):
            raise NotImplementedError(
                "non-empty initial networks are not packed yet"
            )

    # -- static shape helpers ----------------------------------------------

    @property
    def _N(self) -> int:
        return len(self.actors_list)

    @property
    def _E(self) -> int:
        return self.envelope_capacity

    @property
    def _T(self) -> int:
        return len(self.codec.timer_values)

    @property
    def _dup(self) -> bool:
        return self._init_network.kind == UNORDERED_DUPLICATING

    def _timer_bit(self, timer) -> int:
        return self.codec.timer_values.index(timer)

    # -- BatchableModel: shape info ---------------------------------------

    def packed_action_count(self) -> int:
        self._packed_check()
        deliver_drop = self._E * (2 if self._lossy_network else 1)
        return deliver_drop + self._N * self._T

    # -- host <-> packed state conversion ----------------------------------

    def pack_state(self, sys_state: ActorModelState):
        self._packed_check()
        codec = self.codec
        N, E, W, R = self._N, self._E, codec.msg_width, codec.state_width
        rows = np.zeros((N, R), np.uint32)
        for i, actor_state in enumerate(sys_state.actor_states):
            rows[i] = codec.pack_actor_state(i, actor_state)
        timers = np.zeros((N,), np.uint32)
        for i, tset in enumerate(sys_state.timers_set):
            for t in tset:
                timers[i] |= np.uint32(1) << np.uint32(self._timer_bit(t))

        envs = []
        if self._init_network.kind == UNORDERED_NONDUPLICATING:
            items = list(sys_state.network.data.items())
        else:
            items = [(env, 1) for env in sys_state.network.data]
        if len(items) > E:
            raise ValueError(
                f"state has {len(items)} distinct envelopes; "
                f"envelope_capacity={E} is too small"
            )
        for env, count in items:
            envs.append(
                (
                    int(env.src),
                    int(env.dst),
                    tuple(int(x) for x in codec.pack_msg(env.msg)),
                    int(count),
                )
            )
        envs.sort()
        net_src = np.zeros((E,), np.uint32)
        net_dst = np.zeros((E,), np.uint32)
        net_msg = np.zeros((E, W), np.uint32)
        net_cnt = np.zeros((E,), np.uint32)
        for slot, (src, dst, msg, count) in enumerate(envs):
            net_src[slot] = src
            net_dst[slot] = dst
            net_msg[slot] = msg
            net_cnt[slot] = count
        return {
            "rows": rows,
            "timers": timers,
            "net_src": net_src,
            "net_dst": net_dst,
            "net_msg": net_msg,
            "net_cnt": net_cnt,
        }

    def unpack_state(self, packed) -> ActorModelState:
        codec = self.codec
        rows = np.asarray(packed["rows"])
        timers = np.asarray(packed["timers"])
        actor_states = [
            codec.unpack_actor_state(i, rows[i]) for i in range(self._N)
        ]
        timers_set = []
        for i in range(self._N):
            tset = Timers()
            for b, timer in enumerate(codec.timer_values):
                if int(timers[i]) & (1 << b):
                    tset.set(timer)
            timers_set.append(tset)
        network = self._init_network.copy()
        cnt = np.asarray(packed["net_cnt"])
        src = np.asarray(packed["net_src"])
        dst = np.asarray(packed["net_dst"])
        msg = np.asarray(packed["net_msg"])
        for slot in range(self._E):
            if int(cnt[slot]):
                env = Envelope(
                    src=Id(int(src[slot])),
                    dst=Id(int(dst[slot])),
                    msg=codec.unpack_msg(msg[slot]),
                )
                for _ in range(int(cnt[slot])):
                    network.send(env)
        return ActorModelState(
            actor_states=actor_states,
            network=network,
            timers_set=timers_set,
            crashed=[False] * self._N,
            history=None,
        )

    def packed_init_states(self):
        import jax.numpy as jnp

        self._packed_check()
        packed = [self.pack_state(s) for s in self.init_states()]
        return {
            k: jnp.stack([np.asarray(p[k]) for p in packed])
            for k in packed[0]
        }

    # -- traceable transition ----------------------------------------------

    def _canonicalize(self, state):
        """Zeroes empty slots and sorts the envelope table so equal
        multisets produce identical arrays (device analog of the host's
        order-insensitive network hash)."""
        import jax
        import jax.numpy as jnp

        W = self.codec.msg_width
        cnt = state["net_cnt"]
        empty = cnt == 0
        z = jnp.uint32(0)
        src = jnp.where(empty, z, state["net_src"])
        dst = jnp.where(empty, z, state["net_dst"])
        msg = jnp.where(empty[:, None], z, state["net_msg"])
        cnt = jnp.where(empty, z, cnt)
        operands = [empty.astype(jnp.uint32), src, dst]
        operands += [msg[:, w] for w in range(W)]
        operands += [cnt]
        out = jax.lax.sort(tuple(operands), num_keys=len(operands))
        src, dst = out[1], out[2]
        msg = jnp.stack(out[3 : 3 + W], axis=1) if W else msg
        cnt = out[3 + W]
        return {
            "rows": state["rows"],
            "timers": state["timers"],
            "net_src": src,
            "net_dst": dst,
            "net_msg": msg,
            "net_cnt": cnt,
        }

    def _net_send(self, state, src, dst, msg, active):
        """One network send (host ``Network.send``): duplicating nets dedup,
        non-duplicating nets count. Returns (state, overflow)."""
        import jax.numpy as jnp

        src = src.astype(jnp.uint32)
        dst = dst.astype(jnp.uint32)
        cnt = state["net_cnt"]
        match = (
            (state["net_src"] == src)
            & (state["net_dst"] == dst)
            & (state["net_msg"] == msg[None, :]).all(axis=1)
            & (cnt > 0)
        )
        exists = match.any()
        first_match = jnp.argmax(match)
        empty = cnt == 0
        has_empty = empty.any()
        claim = jnp.argmax(empty)

        slot = jnp.where(exists, first_match, claim)
        ok = active & (exists | has_empty)
        if self._dup:
            add = jnp.where(exists, jnp.uint32(0), jnp.uint32(1))
        else:
            add = jnp.uint32(1)
        new_cnt = cnt.at[slot].add(jnp.where(ok, add, jnp.uint32(0)))
        write = ok & ~exists
        state = dict(state)
        state["net_src"] = state["net_src"].at[slot].set(
            jnp.where(write, src, state["net_src"][slot])
        )
        state["net_dst"] = state["net_dst"].at[slot].set(
            jnp.where(write, dst, state["net_dst"][slot])
        )
        state["net_msg"] = state["net_msg"].at[slot].set(
            jnp.where(write, msg, state["net_msg"][slot])
        )
        state["net_cnt"] = new_cnt
        overflow = active & ~exists & ~has_empty
        return state, overflow

    def _apply_callback(self, state, actor, row_new, sends, set_bits, cancel_bits, fired_bit=None):
        """Applies a callback's effects: row write, timer bookkeeping
        (fired timer cleared first, then sets, then cancels — matching the
        host's sequential command processing for set-then-cancel), sends.
        Returns (state, overflow)."""
        import jax.numpy as jnp

        state = dict(state)
        state["rows"] = state["rows"].at[actor].set(row_new)
        t = state["timers"][actor]
        if fired_bit is not None:
            t = t & ~(jnp.uint32(1) << fired_bit.astype(jnp.uint32))
        t = (t | set_bits) & ~cancel_bits
        state["timers"] = state["timers"].at[actor].set(t)
        overflow = jnp.bool_(False)
        for s in range(self.codec.send_capacity):
            dst = sends[s, 0]
            msg = sends[s, 1:]
            active = dst != jnp.uint32(self.codec.SEND_NONE)
            state, ov = self._net_send(
                state, state_src(actor), dst, msg, active
            )
            overflow = overflow | ov
        return state, overflow

    def packed_step(self, state, action_id):
        import jax
        import jax.numpy as jnp

        self._packed_check()
        codec = self.codec
        N, E, T, W = self._N, self._E, self._T, codec.msg_width
        lossy = self._lossy_network
        aid = action_id.astype(jnp.int32)
        msg_branches = codec.on_msg_branches(self)
        timeout_branches = codec.on_timeout_branches(self)
        type_ids = [
            codec.actor_type_id(i, a) for i, a in enumerate(self.actors_list)
        ]
        type_arr = jnp.asarray(type_ids, jnp.int32)

        deliver_ids = E
        drop_ids = E if lossy else 0
        is_deliver = aid < deliver_ids
        is_drop = lossy & (aid >= deliver_ids) & (aid < deliver_ids + drop_ids)
        is_timeout = aid >= deliver_ids + drop_ids

        slot = jnp.clip(jnp.where(is_drop, aid - deliver_ids, aid), 0, E - 1)
        tk = jnp.clip(aid - deliver_ids - drop_ids, 0, N * T - 1)
        t_actor = tk // T
        t_bit = (tk % T).astype(jnp.uint32)

        cnt = state["net_cnt"]
        present = cnt[slot] > 0
        env_src = state["net_src"][slot].astype(jnp.int32)
        env_dst = state["net_dst"][slot].astype(jnp.int32)
        env_msg = state["net_msg"][slot]
        dst_ok = env_dst < N

        # Which actor's callback runs (clamped for safety; masked by valid).
        actor = jnp.clip(jnp.where(is_timeout, t_actor, env_dst), 0, N - 1)
        row = state["rows"][actor]

        def run_msg(args):
            row, actor, src, msg, bit = args
            return jax.lax.switch(
                type_arr[actor],
                [
                    (lambda r, a, s, m, fn=fn: fn(a, r, s, m))
                    for fn in msg_branches
                ],
                row,
                actor,
                src,
                msg,
            )

        def run_timeout(args):
            row, actor, src, msg, bit = args
            return jax.lax.switch(
                type_arr[actor],
                [
                    (lambda r, a, b, fn=fn: fn(a, r, b))
                    for fn in timeout_branches
                ],
                row,
                actor,
                bit,
            )

        row_new, sends, set_bits, cancel_bits, changed = jax.lax.cond(
            is_timeout,
            run_timeout,
            run_msg,
            (row, actor, env_src, env_msg, t_bit),
        )

        no_sends = (sends[:, 0] == codec.SEND_NONE).all()
        no_bits_cmds = (set_bits == 0) & (cancel_bits == 0)
        is_no_op = ~changed & no_sends & no_bits_cmds
        # Host is_no_op_with_timer: unchanged + exactly a renewal of the
        # fired timer.
        renews_only = (
            ~changed
            & no_sends
            & (cancel_bits == 0)
            & (set_bits == (jnp.uint32(1) << t_bit))
        )

        timer_set = (
            (state["timers"][t_actor] >> t_bit) & jnp.uint32(1)
        ) == 1
        valid_deliver = is_deliver & present & dst_ok & ~is_no_op
        valid_drop = is_drop & present
        valid_timeout = is_timeout & timer_set & ~renews_only

        # -- build each outcome and select ----------------------------------

        # Drop: duplicating removes the envelope entirely; counting nets
        # decrement (host Network.on_drop).
        drop_state = dict(state)
        if self._dup:
            drop_state["net_cnt"] = cnt.at[slot].set(jnp.uint32(0))
        else:
            drop_state["net_cnt"] = cnt.at[slot].add(jnp.uint32(0) - 1)

        # Deliver: network effect first (host: on_deliver before
        # process_commands), then callback effects.
        deliver_state = dict(state)
        if not self._dup:
            deliver_state["net_cnt"] = cnt.at[slot].add(jnp.uint32(0) - 1)
        deliver_state, ov_d = self._apply_callback(
            deliver_state, actor, row_new, sends, set_bits, cancel_bits
        )

        timeout_state, ov_t = self._apply_callback(
            dict(state), actor, row_new, sends, set_bits, cancel_bits,
            fired_bit=t_bit,
        )

        overflow = (valid_deliver & ov_d) | (valid_timeout & ov_t)

        def pick(a, b, cond):
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(cond, x, y), a, b
            )

        out = pick(drop_state, deliver_state, is_drop)
        out = pick(timeout_state, out, is_timeout)
        valid = (valid_deliver | valid_drop | valid_timeout) & ~overflow
        # Guard: an invalid lane must still produce canonical arrays.
        out = self._canonicalize(out)
        return out, valid

    def packed_conditions(self):
        self._packed_check()
        conds = self.codec.packed_conditions(self)
        if len(conds) != len(self._properties):
            raise ValueError(
                "codec.packed_conditions must align with the model's "
                f"properties: {len(conds)} != {len(self._properties)}"
            )
        return conds

    def packed_within_boundary(self, state):
        return self.codec.packed_within_boundary(self, state)


def state_src(actor):
    """The sender id for commands emitted by ``actor`` (host: commands are
    processed with ``src = the acting actor``)."""
    import jax.numpy as jnp

    return actor.astype(jnp.int32)
