"""The real-world actor runtime: runs the same ``Actor`` implementations over
UDP sockets with user-supplied serialization (JSON in the examples).

One OS thread per actor; each binds a UDP socket at its Id's encoded address.
The loop computes the earliest timer deadline, uses it as the socket read
timeout, dispatches ``on_msg``/``on_timeout``, and executes output commands
(sends are fire-and-forget datagrams; reliability is added only by the
ordered-reliable-link wrapper).

Reference: ``spawn()`` at ``/root/reference/src/actor/spawn.rs:36-206``.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import Callable, List, Optional, Tuple

from .actor import (
    CANCEL_TIMER,
    SEND,
    SET_TIMER,
    Actor,
    Id,
    Out,
)

# Timers canceled or unset use a far-future deadline sentinel.
_PRACTICALLY_NEVER = 1e18

MAX_DATAGRAM = 65_507  # UDP payload limit


class SpawnHandle:
    """Handle for a spawned actor system; ``join()`` blocks forever (the
    runtime has no shutdown signal, like the reference's crossbeam scope)."""

    def __init__(self, threads: List[threading.Thread], stop: threading.Event):
        self._threads = threads
        self._stop = stop

    def join(self, timeout: Optional[float] = None) -> None:
        for t in self._threads:
            t.join(timeout)

    def stop(self) -> None:
        """Extension over the reference: signal actor loops to exit (checked
        between socket timeouts) so tests can shut the system down."""
        self._stop.set()


def spawn(
    serialize: Callable[[object], bytes],
    deserialize: Callable[[bytes], object],
    actors: List[Tuple[Id, Actor]],
    background: bool = False,
) -> SpawnHandle:
    """Runs actors on UDP sockets at their Id-encoded addresses.

    ``serialize(msg) -> bytes`` and ``deserialize(bytes) -> msg`` define the
    wire format. Returns a handle; with ``background=False`` this blocks until
    interrupted (matching the reference's blocking spawn)."""
    stop = threading.Event()
    threads = []
    for id, actor in actors:
        t = threading.Thread(
            target=_run_actor,
            args=(id, actor, serialize, deserialize, stop),
            name=f"actor-{int(id)}",
            daemon=True,
        )
        t.start()
        threads.append(t)
    handle = SpawnHandle(threads, stop)
    if not background:
        try:
            handle.join()
        except KeyboardInterrupt:
            stop.set()
    return handle


def _run_actor(id: Id, actor: Actor, serialize, deserialize, stop):
    addr = id.socket_addr()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(addr)

    # timer -> absolute deadline (seconds); canceled = _PRACTICALLY_NEVER
    timer_deadlines = {}

    def on_command(c):
        if c.kind == SEND:
            dst, msg = c.args
            data = serialize(msg)
            if len(data) > MAX_DATAGRAM:
                return
            try:
                sock.sendto(data, Id(dst).socket_addr())
            except OSError:
                pass
        elif c.kind == SET_TIMER:
            timer, duration_range = c.args
            lo, hi = duration_range if duration_range else (0.0, 0.0)
            duration = random.uniform(lo, hi) if hi > lo else lo
            timer_deadlines[timer] = time.monotonic() + duration
        elif c.kind == CANCEL_TIMER:
            (timer,) = c.args
            timer_deadlines[timer] = _PRACTICALLY_NEVER

    out = Out()
    state = actor.on_start(id, out)
    for c in out.commands:
        on_command(c)

    while not stop.is_set():
        # Wait until the next timer deadline (or a short poll interval so the
        # stop flag is observed).
        now = time.monotonic()
        deadline = min(timer_deadlines.values(), default=_PRACTICALLY_NEVER)
        wait = max(0.0, min(deadline - now, 0.5))
        sock.settimeout(wait if wait > 0 else 0.000001)
        try:
            data, src_addr = sock.recvfrom(MAX_DATAGRAM)
        except socket.timeout:
            data = None
        except OSError:
            break
        if data is not None:
            try:
                msg = deserialize(data)
            except Exception:
                msg = None
            if msg is not None:
                src = Id.from_socket_addr(src_addr[0], src_addr[1])
                out = Out()
                returned = actor.on_msg(id, state, src, msg, out)
                if returned is not None:
                    state = returned
                for c in out.commands:
                    on_command(c)
        # Fire any expired timers. Re-read the live deadline per timer: an
        # earlier handler in this pass may have canceled or re-set it.
        now = time.monotonic()
        for timer in list(timer_deadlines):
            t_deadline = timer_deadlines.get(timer, _PRACTICALLY_NEVER)
            if t_deadline <= now:
                timer_deadlines[timer] = _PRACTICALLY_NEVER
                out = Out()
                returned = actor.on_timeout(id, state, timer, out)
                if returned is not None:
                    state = returned
                for c in out.commands:
                    on_command(c)
    sock.close()
