"""Actor framework: event-driven actors checked exhaustively (ActorModel) or
run over real UDP (spawn)."""

from .actor import (
    Actor,
    Command,
    Id,
    Out,
    is_no_op,
    is_no_op_with_timer,
)
from .model import (
    ActorModel,
    CrashAction,
    DeliverAction,
    DropAction,
    LOSSLESS,
    LOSSY,
    TimeoutAction,
    model_peers,
    model_timeout,
)
from .model_state import ActorModelState
from .network import Envelope, Network
from .timers import Timers

__all__ = [
    "Actor",
    "ActorModel",
    "ActorModelState",
    "Command",
    "CrashAction",
    "DeliverAction",
    "DropAction",
    "Envelope",
    "Id",
    "LOSSLESS",
    "LOSSY",
    "Network",
    "Out",
    "TimeoutAction",
    "Timers",
    "is_no_op",
    "is_no_op_with_timer",
    "model_peers",
    "model_timeout",
]
