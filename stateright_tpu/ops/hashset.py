"""Device-resident fingerprint set: open addressing with scatter-claim insert.

This replaces the reference's concurrent visited set (``DashMap`` keyed by
fingerprint, ``/root/reference/src/checker/bfs.rs:28-29``) with an XLA-native
structure: a ``(capacity + MAX_PROBES, 2)`` uint32 table of (hi, lo)
fingerprint pairs, linear probing, and batched insert where competing lanes
claim empty slots via a row-window scatter (duplicate scatter indices
resolve to exactly one winning row — XLA applies each update as an atomic
window) and re-read to learn who won. Lanes that lose a claim race keep
probing, exactly like a CAS-loop insert on CPU.

The home slot is *monotone in the key*: ``home = top log2(capacity) bits of
hi``, a multiply-shift hash by a power of two. The checkers always insert
keys in sorted order (the wave dedup sorts them), so consecutive lanes probe
consecutive table regions — turning the per-probe gather/scatter into a
nearly-sequential HBM access pattern instead of random walks over the whole
table. Probes run ``home, home+1, ...`` without wraparound into a
``MAX_PROBES``-row apron past the end (no modulo in the hot loop, and a
future tiled/Pallas kernel never needs a circular window).

Keys must be wave-unique before insertion (dedup by sort upstream) so a
"slot holds my key" observation implies *this lane* inserted or the key was
already present from an earlier wave — the two outcomes the checker needs to
distinguish are disambiguated by ``fresh`` (claim won) vs ``found``.

The all-zero pair is the empty sentinel (fingerprints are never (0, 0) —
see ``ops.fingerprint``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["hashset_new", "hashset_insert", "hashset_contains", "MAX_PROBES"]

# Probe cap per insert; lanes still unplaced after this report overflow and
# the host grows the table. With load factor kept under ~0.6 by the checker,
# linear-probe clusters practically never approach this.
MAX_PROBES = 128


def hashset_new(capacity: int) -> jax.Array:
    """An empty table. ``capacity`` must be a power of two; the allocation
    carries a ``MAX_PROBES``-row apron so probes never wrap."""
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return jnp.zeros((capacity + MAX_PROBES, 2), dtype=jnp.uint32)


def _home(key_hi: jax.Array, capacity: int) -> jax.Array:
    """Monotone home slot: the top ``log2(capacity)`` bits of ``hi``."""
    k = capacity.bit_length() - 1
    if k == 0:
        return jnp.zeros_like(key_hi, dtype=jnp.int32)
    return (key_hi >> jnp.uint32(32 - k)).astype(jnp.int32)


def hashset_insert(
    table: jax.Array,
    key_hi: jax.Array,
    key_lo: jax.Array,
    active: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Inserts a batch of (wave-unique) keys.

    Returns ``(table, fresh, found, overflow)`` where per lane:
    - ``fresh``: this lane claimed an empty slot (key was NOT in the set);
    - ``found``: key was already present;
    - ``overflow``: probe cap exhausted (host must grow and retry).
    Inactive lanes report none of the three.
    """
    capacity = table.shape[0] - MAX_PROBES
    base = _home(key_hi, capacity)

    def cond(carry):
        _table, r, pending, _fresh, _found = carry
        return (r < MAX_PROBES) & pending.any()

    def body(carry):
        table, r, pending, fresh, found = carry
        idx = base + r
        row = table[idx]
        cur_hi, cur_lo = row[:, 0], row[:, 1]
        empty = (cur_hi == 0) & (cur_lo == 0)
        match = (cur_hi == key_hi) & (cur_lo == key_lo)
        found = found | (pending & match)
        attempt = pending & empty & ~match
        # Claim: one full-row update wins per index; losers observe the
        # winner's key on re-read and continue probing. (OOB sentinel must
        # lie past the apron — ``capacity`` itself is a valid apron slot.)
        scatter_idx = jnp.where(attempt, idx, capacity + MAX_PROBES)
        update = jnp.stack([key_hi, key_lo], axis=-1)
        table = table.at[scatter_idx].set(update, mode="drop")
        row2 = table[idx]
        won = attempt & (row2[:, 0] == key_hi) & (row2[:, 1] == key_lo)
        fresh = fresh | won
        pending = pending & ~match & ~won
        return table, r + 1, pending, fresh, found

    n = key_hi.shape[0]
    falses = jnp.zeros((n,), dtype=bool)
    table, _r, pending, fresh, found = jax.lax.while_loop(
        cond, body, (table, jnp.int32(0), active, falses, falses)
    )
    return table, fresh, found, pending


def hashset_contains(
    table: jax.Array, key_hi: jax.Array, key_lo: jax.Array
) -> jax.Array:
    """Batched membership probe (no mutation)."""
    capacity = table.shape[0] - MAX_PROBES
    base = _home(key_hi, capacity)
    n = key_hi.shape[0]

    def cond(carry):
        r, pending, _found = carry
        return (r < MAX_PROBES) & pending.any()

    def body(carry):
        r, pending, found = carry
        idx = base + r
        row = table[idx]
        empty = (row[:, 0] == 0) & (row[:, 1] == 0)
        match = (row[:, 0] == key_hi) & (row[:, 1] == key_lo)
        found = found | (pending & match)
        pending = pending & ~match & ~empty
        return r + 1, pending, found

    _r, _pending, found = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.ones((n,), bool), jnp.zeros((n,), bool))
    )
    return found
