"""Device-resident fingerprint set: open addressing with scatter-claim insert.

This replaces the reference's concurrent visited set (``DashMap`` keyed by
fingerprint, ``/root/reference/src/checker/bfs.rs:28-29``) with an XLA-native
structure: a ``(capacity + MAX_PROBES, 2)`` uint32 table of (hi, lo)
fingerprint pairs, linear probing, and batched insert where competing lanes
claim empty slots via a row-window scatter (duplicate scatter indices
resolve to exactly one winning row — XLA applies each update as an atomic
window) and re-read to learn who won. Lanes that lose a claim race keep
probing, exactly like a CAS-loop insert on CPU.

The home slot is *monotone in the key*: ``home = top log2(capacity) bits of
hi``, a multiply-shift hash by a power of two. The checkers always insert
keys in sorted order (the wave dedup sorts them), so consecutive lanes probe
consecutive table regions — turning the per-probe gather/scatter into a
nearly-sequential HBM access pattern instead of random walks over the whole
table. Probes run ``home, home+1, ...`` without wraparound into a
``MAX_PROBES``-row apron past the end (no modulo in the hot loop, and a
future tiled/Pallas kernel never needs a circular window).

Keys must be wave-unique before insertion (dedup by sort upstream) so a
"slot holds my key" observation implies *this lane* inserted or the key was
already present from an earlier wave — the two outcomes the checker needs to
distinguish are disambiguated by ``fresh`` (claim won) vs ``found``.

The all-zero pair is the empty sentinel (fingerprints are never (0, 0) —
see ``ops.fingerprint``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "hashset_new",
    "hashset_insert",
    "hashset_insert_unsorted",
    "hashset_insert_salted",
    "hashset_contains",
    "hashset_probe_length_counts",
    "MAX_PROBES",
]

# Probe cap per insert; lanes still unplaced after this report overflow and
# the host grows the table. With load factor kept under ~0.6 by the checker,
# linear-probe clusters practically never approach this.
MAX_PROBES = 128


def hashset_new(capacity: int) -> jax.Array:
    """An empty table. ``capacity`` must be a power of two; the allocation
    carries a ``MAX_PROBES``-row apron so probes never wrap."""
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return jnp.zeros((capacity + MAX_PROBES, 2), dtype=jnp.uint32)


def _home(key_hi: jax.Array, capacity: int) -> jax.Array:
    """Monotone home slot: the top ``log2(capacity)`` bits of ``hi``."""
    k = capacity.bit_length() - 1
    if k == 0:
        return jnp.zeros_like(key_hi, dtype=jnp.int32)
    return (key_hi >> jnp.uint32(32 - k)).astype(jnp.int32)


def hashset_insert(
    table: jax.Array,
    key_hi: jax.Array,
    key_lo: jax.Array,
    active: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Inserts a batch of (wave-unique) keys.

    Returns ``(table, fresh, found, overflow)`` where per lane:
    - ``fresh``: this lane claimed an empty slot (key was NOT in the set);
    - ``found``: key was already present;
    - ``overflow``: probe cap exhausted (host must grow and retry).
    Inactive lanes report none of the three.
    """
    capacity = table.shape[0] - MAX_PROBES
    base = _home(key_hi, capacity)

    def cond(carry):
        _table, r, pending, _fresh, _found = carry
        return (r < MAX_PROBES) & pending.any()

    def body(carry):
        table, r, pending, fresh, found = carry
        idx = base + r
        row = table[idx]
        cur_hi, cur_lo = row[:, 0], row[:, 1]
        empty = (cur_hi == 0) & (cur_lo == 0)
        match = (cur_hi == key_hi) & (cur_lo == key_lo)
        found = found | (pending & match)
        attempt = pending & empty & ~match
        # Claim: one full-row update wins per index; losers observe the
        # winner's key on re-read and continue probing. (OOB sentinel must
        # lie past the apron — ``capacity`` itself is a valid apron slot.)
        scatter_idx = jnp.where(attempt, idx, capacity + MAX_PROBES)
        update = jnp.stack([key_hi, key_lo], axis=-1)
        table = table.at[scatter_idx].set(update, mode="drop")
        row2 = table[idx]
        won = attempt & (row2[:, 0] == key_hi) & (row2[:, 1] == key_lo)
        fresh = fresh | won
        pending = pending & ~match & ~won
        return table, r + 1, pending, fresh, found

    n = key_hi.shape[0]
    falses = jnp.zeros((n,), dtype=bool)
    table, _r, pending, fresh, found = jax.lax.while_loop(
        cond, body, (table, jnp.int32(0), active, falses, falses)
    )
    return table, fresh, found, pending


def hashset_insert_unsorted(
    table: jax.Array,
    key_hi: jax.Array,
    key_lo: jax.Array,
    active: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """``hashset_insert`` without the wave-unique precondition: the batch
    may contain DUPLICATE active keys in any order, and exactly one lane
    per distinct key reports ``fresh``.

    Two consumers ride this variant: the scatter wave-dedup policy
    below, and the swarm engine's visited-sample table
    (``checker/swarm.py`` — walk fingerprints arrive unsorted and
    duplicated by construction, and the exactly-one-fresh guarantee is
    what makes ``unique_sample`` an honest distinct count).

    Same-key lanes attempt the same slot; the row-window claim alone
    cannot tell them apart (each re-reads its own key either way), so a
    table-sized *owner ticket* scratch — scatter-min of lane ids per slot
    — breaks the tie: a lane's claim is fresh only if its ticket
    survived. Duplicate losers observe a key match on the next round and
    resolve as ``found``. The scratch costs one table-shaped memset +
    one extra scatter/gather per probe round; what it buys is dropping
    the wave's ``lax.sort`` over the full F x A candidate grid, which
    dominates wide waves (66% of the 2pc-7 wave at F=8192 on CPU —
    ``checker/tpu.py`` exposes the trade as ``wave_dedup``). The sorted
    variant keeps its nearly-sequential probe pattern and stays the
    default until the scatter pattern is measured on TPU HBM.

    ``found`` counts duplicate losers as found-in-set (indistinguishable
    from an earlier-wave hit by design; the checkers only consume
    ``fresh``).

    Two phases: 2 bulk probe rounds at full batch width resolve the vast
    majority of lanes (measured on a realistic 2pc-7 wave: 303K lanes
    drop to ~5% pending after 2 rounds, yet the probe tail runs ~27
    rounds), then the stragglers are COMPACTED to a quarter-width batch
    for the remaining rounds — XLA cannot skip masked lanes, so without
    the compaction every straggler round pays full-width table traffic
    (2pc-7 leg: 61.7K -> 72.9K states/s). Straggler lanes beyond the
    compact width (pathological loads only — the n/4 width is ~5x the
    measured straggler count) report as pending, which the checker
    already answers with grow-and-retry; growth shortens probe chains,
    so the loop terminates. Fewer bulk rounds measured faster still but
    only by overflowing real lanes into spurious table growth — not
    worth the fragility. Phase boundaries cannot split same-key twins
    (same key => same home => lockstep rounds). Both phases run inside
    while_loops (NOT unrolled) so XLA aliases the table/owner carries;
    an unrolled bulk phase measured slower than no split at all (each
    round copied the multi-MB table).
    """
    capacity = table.shape[0] - MAX_PROBES
    n = key_hi.shape[0]
    owner0 = jnp.full((table.shape[0],), jnp.uint32(0xFFFFFFFF))
    oob = capacity + MAX_PROBES
    bulk_rounds = 2
    m = max(128, n // 4)  # straggler width

    def round_step(carry_table, owner, r, khi, klo, kbase, pending, lanes):
        idx = kbase + r
        row = carry_table[idx]
        empty = (row[:, 0] == 0) & (row[:, 1] == 0)
        match = (row[:, 0] == khi) & (row[:, 1] == klo)
        found_now = pending & match
        attempt = pending & empty & ~match
        scatter_idx = jnp.where(attempt, idx, oob)
        update = jnp.stack([khi, klo], axis=-1)
        carry_table = carry_table.at[scatter_idx].set(update, mode="drop")
        row2 = carry_table[idx]
        key_won = attempt & (row2[:, 0] == khi) & (row2[:, 1] == klo)
        # Ticket tie-break ONLY among lanes whose key actually landed
        # (same-key twins): a different-key contender must not write a
        # ticket, or the table-write winner and ticket winner could
        # disagree and a landed key would end up with no fresh lane (a
        # silently lost state).
        owner = owner.at[jnp.where(key_won, idx, oob)].min(
            lanes, mode="drop"
        )
        won = key_won & (owner[idx] == lanes)
        # Duplicate losers whose key DID land resolve as found and stop;
        # different-key losers keep probing.
        pending = pending & ~match & ~key_won
        return carry_table, owner, pending, won, found_now | (key_won & ~won)

    # Phase 1: bulk rounds at full width. A while_loop (not an unrolled
    # python loop) so XLA aliases the table/owner carries in place —
    # unrolled rounds were measured SLOWER than the single-phase loop
    # (each round copied the multi-MB table).
    base = _home(key_hi, capacity)
    lane = jnp.arange(n, dtype=jnp.uint32)
    falses = jnp.zeros((n,), dtype=bool)

    def probe_loop(khi, klo, kbase, lanes, carry, stop):
        """Probe rounds at the carry's width until ``stop`` (or resolved).
        One definition serves the bulk phase, the small-batch finish, and
        the straggler phase."""

        def cond(c):
            _t, _o, r, pending, _f, _fo = c
            return (r < stop) & pending.any()

        def body(c):
            t, o, r, pending, f, fo = c
            t, o, pending, won, fnow = round_step(
                t, o, r, khi, klo, kbase, pending, lanes
            )
            return t, o, r + 1, pending, f | won, fo | fnow

        return jax.lax.while_loop(cond, body, carry)

    two_phase = m < n and bulk_rounds < MAX_PROBES
    stop1 = min(bulk_rounds, MAX_PROBES) if two_phase else MAX_PROBES
    table, owner, _r, pending, fresh, found = probe_loop(
        key_hi, key_lo, base, lane,
        (table, owner0, jnp.int32(0), active, falses, falses),
        stop1,
    )
    if not two_phase:
        return table, fresh, found, pending

    # Phase 2: compact stragglers to width m and finish there.
    pos = jnp.cumsum(pending.astype(jnp.int32)) - 1
    kept = pending & (pos < m)
    over = pending & (pos >= m)
    slot = jnp.where(kept, pos, m)
    khi2 = jnp.zeros((m,), jnp.uint32).at[slot].set(key_hi, mode="drop")
    klo2 = jnp.zeros((m,), jnp.uint32).at[slot].set(key_lo, mode="drop")
    base2 = jnp.zeros((m,), jnp.int32).at[slot].set(base, mode="drop")
    # Padding slots MUST hold an out-of-bounds lane id (n), not 0: the
    # scatter-back below indexes original lanes by lane2, and a padding
    # slot aliasing real lane 0 would clobber its outcome with False
    # (duplicate-index .set is last-write-wins, NOT an OR — a straggler
    # lane 0 would silently lose its fresh/pending bit and the state
    # would never be expanded or retried).
    lane2 = jnp.full((m,), n, jnp.uint32).at[slot].set(lane, mode="drop")
    act2 = jnp.zeros((m,), bool).at[slot].set(kept, mode="drop")

    mfalses = jnp.zeros((m,), dtype=bool)
    table, _o, _r, pending2, fresh2, found2 = probe_loop(
        khi2, klo2, base2, lane2,
        (table, owner, jnp.int32(bulk_rounds), act2, mfalses, mfalses),
        MAX_PROBES,
    )
    # Scatter straggler outcomes back to their original lanes (each kept
    # lane is a distinct original and padding indexes drop, so .set under
    # the OR is exact).
    li = lane2.astype(jnp.int32)
    fresh = fresh | falses.at[li].set(fresh2 & act2, mode="drop")
    found = found | falses.at[li].set(found2 & act2, mode="drop")
    pending_out = over | falses.at[li].set(pending2 & act2, mode="drop")
    return table, fresh, found, pending_out


def hashset_insert_salted(
    table: jax.Array,
    key_hi: jax.Array,
    key_lo: jax.Array,
    salt_hi: jax.Array,
    salt_lo: jax.Array,
    active: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Tenant-salted visited-set insert for a packed multi-tenant wave
    (``checker/packed_tenancy.py``): each lane's (hi, lo) fingerprint is
    XOR-salted by its tenant's per-lane salt before claiming, so many
    tenants share ONE table without cross-tenant dedup (see
    ``ops.fingerprint.salt_keys`` for why XOR keeps every tenant's dedup
    bit-identical to its solo run).

    Built on the duplicate-tolerant UNSORTED insert on purpose: sorting
    by salted key would interleave tenants' lanes in salt order, but the
    owner-ticket scatter insert keeps natural lane order — so each
    tenant's fresh lanes come out in its own FIFO frontier order, the
    exact claim order its solo run (``wave_dedup="scatter"``, the CPU
    backend default) produces. That order-preservation is what makes the
    packed run's parent pointers, discovery fingerprints, and golden
    reports per-tenant bit-identical, not just count-identical.
    """
    from .fingerprint import salt_keys

    shi, slo = salt_keys(key_hi, key_lo, salt_hi, salt_lo)
    return hashset_insert_unsorted(table, shi, slo, active)


def hashset_probe_length_counts(table):
    """Probe-chain length distribution of the RESIDENT keys: for each
    occupied slot, the displacement from its key's home slot (linear
    probing never wraps, so ``slot - home`` IS the probe count that
    insert paid and every future lookup repays). Returns an int64 array
    of length ``MAX_PROBES + 1`` where index ``d`` counts keys resting
    ``d`` slots past home.

    Audit path, not hot: pure numpy over a host copy of the table (the
    attribution engine pulls it once at run end). The distribution is
    the observed cost of the probabilistic machinery — a heavy tail here
    means key clustering is eroding the nearly-sequential probe pattern
    the sorted insert is built around."""
    import numpy as np

    tab = np.asarray(table)
    capacity = tab.shape[0] - MAX_PROBES
    live = (tab[:, 0] != 0) | (tab[:, 1] != 0)
    idx = np.flatnonzero(live)
    counts = np.zeros(MAX_PROBES + 1, np.int64)
    if len(idx) == 0:
        return counts
    k = capacity.bit_length() - 1
    if k == 0:
        home = np.zeros(len(idx), np.int64)
    else:
        home = (
            tab[idx, 0].astype(np.uint32) >> np.uint32(32 - k)
        ).astype(np.int64)
    disp = np.clip(idx - home, 0, MAX_PROBES)
    return np.bincount(disp, minlength=MAX_PROBES + 1)


def hashset_contains(
    table: jax.Array, key_hi: jax.Array, key_lo: jax.Array
) -> jax.Array:
    """Batched membership probe (no mutation)."""
    capacity = table.shape[0] - MAX_PROBES
    base = _home(key_hi, capacity)
    n = key_hi.shape[0]

    def cond(carry):
        r, pending, _found = carry
        return (r < MAX_PROBES) & pending.any()

    def body(carry):
        r, pending, found = carry
        idx = base + r
        row = table[idx]
        empty = (row[:, 0] == 0) & (row[:, 1] == 0)
        match = (row[:, 0] == key_hi) & (row[:, 1] == key_lo)
        found = found | (pending & match)
        pending = pending & ~match & ~empty
        return r + 1, pending, found

    _r, _pending, found = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.ones((n,), bool), jnp.zeros((n,), bool))
    )
    return found
