"""Fused Pallas wave megakernel: one kernel per BFS wave.

The staged wave (``checker/tpu.TpuBfsChecker._wave``) is one jit but ~5
logical XLA stages — expand, fingerprint, sort-dedup, visited-set insert,
compact/properties/coverage — each materializing its intermediates through
HBM, and (with ``hashset_impl="pallas"``) a separate Pallas dispatch for the
insert. BENCH_r11 measured the consequence: device utilization 0.10,
``gap_share`` 0.57 — per-stage dispatch overhead and HBM round-trips
dominate, and no host/device overlap fixes that. GPUexplore's answer
(PAPERS: "On the Scalability of the GPUexplore Explicit-State Model
Checker") is to run the entire BFS iteration inside a single kernel against
a fast-memory hash table; this module is that design on the TPU memory
hierarchy.

One ``pl.pallas_call`` grids over the visited table's ``TILE_ROWS``-row
tiles. Grid step ``t`` sweeps table tile ``t`` as a VMEM-resident
partition while the next tile's window is double-buffered in via async
DMA; the wave-wide compute rides the first and last steps:

- **prologue** (step 0): expand the F × A action grid, boundary-filter,
  fingerprint, sort-dedup (the staged path's exact stable
  ``lax.sort``), evaluate property conditions, and compute each tile's
  contiguous key range (``searchsorted`` over the monotone homes) into
  scratch. The model's packed callables close over device arrays
  (action tables, hash constants); the whole prologue goes through
  ``jax.closure_convert`` and the hoisted constants ride in as extra
  VMEM operands, since a Pallas kernel cannot capture array constants;
- **every step**: wait for tile ``t``'s window DMA, patch the
  ``MAX_PROBES``-row apron from the previous tile's buffer (tile ``t``'s
  window was prefetched *before* tile ``t-1``'s claims were written
  back, and the two windows overlap by exactly the apron), start the
  prefetch of tile ``t+1`` into the opposite parity buffer, then
  probe/claim this tile's keys in VMEM (``pallas_hashset.probe_claim``
  — the identical claim semantics as the staged insert) and write the
  window back;
- **epilogue** (last step): prefix-compact the fresh lanes, evaluate
  properties, reduce coverage, and emit the consolidated stats vector.

The output dict is bit-identical to the staged wave's — same sort, same
first-occurrence dedup winner, same claim order, same compaction — so
every consumer (deep drain, checkpointing, tiered store, AOT cache)
composes unchanged. ``interpret=True`` (forced off-TPU) runs the real
kernel logic on CPU for tier-1/CI; the in-kernel ``lax.sort``/gathers do
not yet have a Mosaic lowering, so compiled-TPU support is gated on the
interpret flag (see README "Fused wave megakernel").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .hashset import MAX_PROBES
from .pallas_hashset import _KC, TILE_ROWS, _compiler_params, probe_claim

__all__ = ["FusedWaveSpec", "fused_wave"]

# numpy scalar: folds into jaxprs as a literal, never a captured constant.
_U32_MAX = np.uint32(0xFFFFFFFF)
# One table window: the tile plus the probe apron reaching into the next
# tile (open addressing probes at most MAX_PROBES rows past the home).
_WIN = TILE_ROWS + MAX_PROBES


@dataclasses.dataclass(frozen=True, eq=False)
class FusedWaveSpec:
    """Everything the fused wave closes over, bundled so the kernel stays
    checker-agnostic. ``expectations`` carry the property kinds as strings
    (``"always" | "sometimes" | "eventually"``) and ``ebit`` the
    (property index → eventually bit) pairs — the ops layer must not
    import checker/core enums. ``cov_layout`` is a
    ``telemetry.coverage.DeviceCoverage`` (or None); ``cov_antecedents``
    align with properties when coverage is on."""

    expand: Callable
    within_boundary: Callable
    fp_fn: Callable
    conditions: Tuple[Callable, ...]
    expectations: Tuple[str, ...]
    ebit: Tuple[Tuple[int, int], ...]
    action_count: int
    cov_layout: Any = None
    cov_antecedents: Tuple[Optional[Callable], ...] = ()
    interpret: bool = True


def fused_wave(spec: FusedWaveSpec, table, states, hi, lo, ebits, depth,
               mask, depth_cap):
    """One fused wave. Same arguments and output dict as the staged
    ``TpuBfsChecker._wave`` (materializing, no symmetry/fps/liveness —
    the checker refuses those combinations up front), traced inside the
    caller's jit."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    A = spec.action_count
    F = hi.shape[0]
    B = F * A
    P = len(spec.conditions)
    ebit = dict(spec.ebit)
    cov = spec.cov_layout
    capacity = table.shape[0] - MAX_PROBES
    cap_bits = capacity.bit_length() - 1
    assert capacity == (1 << cap_bits), "capacity must be a power of two"
    assert capacity % TILE_ROWS == 0, (
        f"capacity must be a multiple of TILE_ROWS={TILE_ROWS} "
        "(round_table_capacity)"
    )
    n_tiles = capacity // TILE_ROWS
    n_stats = 4 + (1 if P else 0)

    state_leaves, state_tree = jax.tree_util.tree_flatten(states)
    n_state = len(state_leaves)
    cand_struct = jax.eval_shape(jax.vmap(spec.expand), states)[0]
    cand_leaf_structs, cand_tree = jax.tree_util.tree_flatten(cand_struct)
    n_cand = len(cand_leaf_structs)
    cand_flat_shapes = [
        ((B,) + s.shape[2:], s.dtype) for s in cand_leaf_structs
    ]

    def prologue(dcap, hi_v, lo_v, ebits_v, depth_v, mask_u, *sleaves):
        """The wave-wide compute ahead of the table sweep, as a pure
        function of the kernel inputs — every model closure (expand,
        boundary, fingerprint, conditions, coverage antecedents) lives
        here so ``closure_convert`` can hoist their captured arrays."""
        states_v = jax.tree_util.tree_unflatten(state_tree, list(sleaves))
        eval_mask = (mask_u != 0) & (depth_v < dcap)
        cond_vals = [jax.vmap(c)(states_v) for c in spec.conditions]
        ebits_after = ebits_v
        for pi, b in ebit.items():
            ebits_after = jnp.where(
                cond_vals[pi],
                ebits_after & ~jnp.uint32(1 << b),
                ebits_after,
            )
        cand, cvalid = jax.vmap(spec.expand)(states_v)
        cvalid = cvalid & eval_mask[:, None]
        cvalid = cvalid & jax.vmap(jax.vmap(spec.within_boundary))(cand)
        terminal = eval_mask & ~cvalid.any(axis=1)
        cond_mat = (
            jnp.stack([c.astype(jnp.uint32) for c in cond_vals])
            if P
            else jnp.zeros((0, F), jnp.uint32)
        )
        # Coverage exercise masks need the frontier states, so they are
        # computed here (not in the epilogue) and parked in scratch.
        ex_mat = jnp.zeros((0, F), jnp.uint32)
        if cov is not None and P:
            exercised = []
            for pi in range(P):
                kind = spec.expectations[pi]
                if kind == "always":
                    ant = (
                        spec.cov_antecedents[pi]
                        if spec.cov_antecedents
                        else None
                    )
                    exercised.append(
                        eval_mask & jax.vmap(ant)(states_v)
                        if ant is not None
                        else eval_mask
                    )
                elif kind == "sometimes":
                    exercised.append(eval_mask & cond_vals[pi])
                else:  # eventually: met == the unmet bit already cleared
                    eb = ebit[pi]
                    exercised.append(
                        eval_mask
                        & (((ebits_after >> jnp.uint32(eb)) & 1) == 0)
                    )
            ex_mat = jnp.stack([e.astype(jnp.uint32) for e in exercised])
        cand_flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        cvalid_flat = cvalid.reshape(B)
        chi, clo = jax.vmap(spec.fp_fn)(cand_flat)
        # The staged path's exact stable dedup sort: invalid lanes sink
        # to the all-ones sentinel, first occurrence of each (hi, lo)
        # wins.
        shi = jnp.where(cvalid_flat, chi, _U32_MAX)
        slo = jnp.where(cvalid_flat, clo, _U32_MAX)
        shi, slo, sidx = jax.lax.sort(
            (shi, slo, jnp.arange(B, dtype=jnp.int32)), num_keys=2
        )
        uniq = jnp.concatenate(
            [
                jnp.ones((1,), bool),
                (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1]),
            ]
        )
        active = (cvalid_flat[sidx] & uniq).astype(jnp.uint32)
        # Per-tile key ranges: homes are monotone in the sorted keys (top
        # cap_bits of hi), so each tile's keys form a contiguous range.
        # Sentinel lanes home into the last tile, masked by ``active``.
        homes = (shi >> jnp.uint32(32 - cap_bits)).astype(jnp.int32)
        bounds = jnp.arange(1, n_tiles + 1, dtype=jnp.int32) * TILE_ROWS
        starts = jnp.concatenate(
            [
                jnp.zeros((1,), jnp.int32),
                jnp.searchsorted(homes, bounds).astype(jnp.int32),
            ]
        )
        return (
            ebits_after,
            eval_mask.astype(jnp.uint32),
            terminal.astype(jnp.uint32),
            cond_mat,
            ex_mat,
            cvalid_flat.astype(jnp.uint32),
            chi,
            clo,
            shi,
            slo,
            sidx,
            active,
            starts,
        ) + tuple(jax.tree_util.tree_leaves(cand_flat))

    # A Pallas kernel cannot capture array constants (the model's packed
    # callables close over action tables, hash coefficient vectors, …);
    # stage the prologue to a jaxpr, hoist its constants, and feed them
    # in as ordinary VMEM operands, rank-1-padded. (``jax.closure_convert``
    # is not enough: it only hoists consts that are AD-perturbable
    # tracers, and the model's concrete arrays stay baked in.)
    dcap = jnp.asarray(depth_cap, jnp.int32)
    mask_u = mask.astype(jnp.uint32)
    closed = jax.make_jaxpr(prologue)(
        dcap, hi, lo, ebits, depth, mask_u, *state_leaves
    )
    consts = closed.consts
    n_args = 6 + n_state

    def prologue_conv(*args_and_consts):
        from jax.core import eval_jaxpr

        return eval_jaxpr(
            closed.jaxpr,
            args_and_consts[n_args:],
            *args_and_consts[:n_args],
        )

    const_shapes = [jnp.shape(c) for c in consts]
    const_ops = [jnp.reshape(c, (1,) + jnp.shape(c)) for c in consts]
    n_const = len(const_ops)

    def kernel(*refs):
        dcap_ref = refs[0]
        srefs = refs[1 : 1 + n_state]
        o = 1 + n_state
        hi_ref, lo_ref, ebits_ref, depth_ref, mask_ref = refs[o : o + 5]
        o += 5
        const_refs = refs[o : o + n_const]
        o += n_const + 1  # + the aliased table input (DMA via the output)
        out_table = refs[o]
        o += 1
        new_srefs = refs[o : o + n_cand]
        o += n_cand
        (new_hi_ref, new_lo_ref, new_ebits_ref, new_depth_ref,
         parent_hi_ref, parent_lo_ref) = refs[o : o + 6]
        o += 6
        if P:
            hit_ref, prop_hi_ref, prop_lo_ref = refs[o : o + 3]
            o += 3
        if cov is not None:
            cov_ref = refs[o]
            o += 1
        stats_ref = refs[o]
        o += 1
        cand_refs = refs[o : o + n_cand]
        o += n_cand
        (chi_s, clo_s, shi_s, slo_s, cvalid_s, active_s, fresh_s,
         pending_s) = refs[o : o + 8]
        o += 8
        sidx_s, ebits_after_s, evalm_s, term_s = refs[o : o + 4]
        o += 4
        if P:
            cond_s = refs[o]
            o += 1
        if cov is not None and P:
            ex_s = refs[o]
            o += 1
        starts_s = refs[o]
        o += 1
        win_a, win_b, sem_a, sem_b, sem_out = refs[o : o + 5]

        t = pl.program_id(0)

        @pl.when(t == 0)
        def _prologue():
            const_vals = [
                r[...].reshape(s)
                for r, s in zip(const_refs, const_shapes)
            ]
            outs = prologue_conv(
                dcap_ref[0],
                hi_ref[...],
                lo_ref[...],
                ebits_ref[...],
                depth_ref[...],
                mask_ref[...],
                *[r[...] for r in srefs],
                *const_vals,
            )
            (ebits_after, evalm, term, cond_mat, ex_mat, cvalid_u, chi,
             clo, shi, slo, sidx, active, starts) = outs[:13]
            ebits_after_s[...] = ebits_after
            evalm_s[...] = evalm
            term_s[...] = term
            if P:
                cond_s[...] = cond_mat
            if cov is not None and P:
                ex_s[...] = ex_mat
            cvalid_s[...] = cvalid_u
            chi_s[...] = chi
            clo_s[...] = clo
            shi_s[...] = shi
            slo_s[...] = slo
            sidx_s[...] = sidx
            active_s[...] = active
            fresh_s[...] = jnp.zeros((B,), jnp.uint32)
            pending_s[...] = jnp.zeros((B,), jnp.uint32)
            starts_s[...] = starts
            for ref, leaf in zip(cand_refs, outs[13:]):
                ref[...] = leaf

            # Kick off tile 0's window DMA (parity buffer A).
            @pl.when(starts_s[1] > starts_s[0])
            def _first_dma():
                pltpu.make_async_copy(
                    out_table.at[pl.ds(0, _WIN)], win_a, sem_a
                ).start()

        s = starts_s[t]
        e = starts_s[t + 1]
        even = t % 2 == 0
        # Tile t-1 processed ⇒ its claims into THIS tile's first
        # MAX_PROBES rows (the window overlap) postdate our window
        # prefetch; the freshest copy of those rows lives in the previous
        # parity buffer's apron.
        tm1 = jnp.maximum(t - 1, 0)
        patch_needed = (t > 0) & (starts_s[t] > starts_s[tm1])

        def wait_and_patch(buf, prev_buf, sem):
            pltpu.make_async_copy(
                out_table.at[pl.ds(t * TILE_ROWS, _WIN)], buf, sem
            ).wait()

            @pl.when(patch_needed)
            def _patch():
                buf[pl.ds(0, MAX_PROBES), :] = prev_buf[
                    pl.ds(TILE_ROWS, MAX_PROBES), :
                ]

        @pl.when(e > s)
        def _wait():
            @pl.when(even)
            def _a():
                wait_and_patch(win_a, win_b, sem_a)

            @pl.when(~even)
            def _b():
                wait_and_patch(win_b, win_a, sem_b)

        # Prefetch tile t+1 into the opposite parity buffer — after the
        # apron patch above consumed that buffer's previous contents.
        nxt = t + 1

        @pl.when(nxt < n_tiles)
        def _prefetch():
            @pl.when(starts_s[nxt + 1] > starts_s[nxt])
            def _issue():
                src = out_table.at[pl.ds(nxt * TILE_ROWS, _WIN)]

                @pl.when(even)  # next tile is odd parity
                def _b():
                    pltpu.make_async_copy(src, win_b, sem_b).start()

                @pl.when(~even)
                def _a():
                    pltpu.make_async_copy(src, win_a, sem_a).start()

        def sweep(buf):
            base = t * TILE_ROWS
            shift = jnp.uint32(32 - cap_bits)

            def chunk_body(c, _):
                k0 = s + c * _KC

                def key_body(k, _):
                    i = k0 + k

                    @pl.when((i < e) & (active_s[i] != 0))
                    def _one_key():
                        kh = shi_s[i]
                        kl = slo_s[i]
                        local = (kh >> shift).astype(jnp.int32) - base
                        can_claim, is_found = probe_claim(
                            buf, kh, kl, local
                        )
                        fresh_s[i] = can_claim.astype(jnp.uint32)
                        pending_s[i] = (~is_found & ~can_claim).astype(
                            jnp.uint32
                        )

                jax.lax.fori_loop(0, _KC, key_body, None)
                return 0

            n_chunks = (e - s + _KC - 1) // _KC
            jax.lax.fori_loop(0, n_chunks, chunk_body, 0)
            dma_out = pltpu.make_async_copy(
                buf, out_table.at[pl.ds(base, _WIN)], sem_out
            )
            dma_out.start()
            dma_out.wait()

        @pl.when(e > s)
        def _sweep():
            @pl.when(even)
            def _a():
                sweep(win_a)

            @pl.when(~even)
            def _b():
                sweep(win_b)

        @pl.when(t == n_tiles - 1)
        def _epilogue():
            fresh = fresh_s[...] != 0
            sidx = sidx_s[...]
            chi = chi_s[...]
            clo = clo_s[...]
            ebits_after = ebits_after_s[...]
            depth_v = depth_ref[...]
            mask_v = mask_ref[...] != 0
            eval_mask = evalm_s[...] != 0
            terminal = term_s[...] != 0
            hi_v = hi_ref[...]
            lo_v = lo_ref[...]

            pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
            out_slot = jnp.where(fresh, pos, B)
            zi = jnp.zeros((B,), jnp.int32)
            zu = jnp.zeros((B,), jnp.uint32)
            src_idx = zi.at[out_slot].set(sidx, mode="drop")
            parent_row = sidx // A
            new_hi_ref[...] = zu.at[out_slot].set(chi[sidx], mode="drop")
            new_lo_ref[...] = zu.at[out_slot].set(clo[sidx], mode="drop")
            new_ebits_ref[...] = zu.at[out_slot].set(
                ebits_after[parent_row], mode="drop"
            )
            new_depth_ref[...] = zi.at[out_slot].set(
                depth_v[parent_row] + 1, mode="drop"
            )
            parent_hi_ref[...] = zu.at[out_slot].set(
                hi_v[parent_row], mode="drop"
            )
            parent_lo_ref[...] = zu.at[out_slot].set(
                lo_v[parent_row], mode="drop"
            )
            for out_ref, cref in zip(new_srefs, cand_refs):
                out_ref[...] = cref[...][src_idx]

            generated = (cvalid_s[...] != 0).sum(dtype=jnp.int32)
            n_new = fresh.sum(dtype=jnp.int32)
            overflow = (pending_s[...] != 0).sum(dtype=jnp.int32)
            max_depth = jnp.max(jnp.where(mask_v, depth_v, 0))

            hits = []
            if P:
                fhis, flos = [], []
                for i in range(P):
                    kind = spec.expectations[i]
                    cv = cond_s[i, :] != 0
                    if kind == "always":
                        h = eval_mask & ~cv
                    elif kind == "sometimes":
                        h = eval_mask & cv
                    else:  # eventually: unmet bit at a terminal state
                        b = ebit[i]
                        h = terminal & (
                            ((ebits_after >> jnp.uint32(b)) & 1) == 1
                        )
                    idx = jnp.argmax(h)
                    hits.append(h.any())
                    fhis.append(hi_v[idx])
                    flos.append(lo_v[idx])
                hit_ref[...] = jnp.stack(hits).astype(jnp.int32)
                prop_hi_ref[...] = jnp.stack(fhis)
                prop_lo_ref[...] = jnp.stack(flos)

            if cov is not None:
                exercised = (
                    [ex_s[i, :] != 0 for i in range(P)] if P else []
                )
                cov_ref[...] = cov.wave_reduce(
                    eval_mask=eval_mask,
                    cvalid=(cvalid_s[...] != 0).reshape(F, A),
                    fresh=fresh,
                    lane_action=sidx % A,
                    new_depth=depth_v[sidx // A] + 1,
                    exercised=exercised,
                    uniq_fp=None,
                    uniq_key=None,
                )

            stats = [generated, n_new, overflow, max_depth]
            if P:
                stats.append(jnp.stack(hits).any().astype(jnp.int32))
            stats_ref[...] = jnp.stack(
                [x.astype(jnp.int32) for x in stats]
            )

    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    any_ = pl.BlockSpec(memory_space=pl.ANY)
    out_shape = [jax.ShapeDtypeStruct(table.shape, table.dtype)]
    out_shape += [
        jax.ShapeDtypeStruct(shape, dtype)
        for shape, dtype in cand_flat_shapes
    ]
    out_shape += [
        jax.ShapeDtypeStruct((B,), jnp.uint32),  # new hi
        jax.ShapeDtypeStruct((B,), jnp.uint32),  # new lo
        jax.ShapeDtypeStruct((B,), jnp.uint32),  # new ebits
        jax.ShapeDtypeStruct((B,), jnp.int32),  # new depth
        jax.ShapeDtypeStruct((B,), jnp.uint32),  # parent hi
        jax.ShapeDtypeStruct((B,), jnp.uint32),  # parent lo
    ]
    if P:
        out_shape += [
            jax.ShapeDtypeStruct((P,), jnp.int32),
            jax.ShapeDtypeStruct((P,), jnp.uint32),
            jax.ShapeDtypeStruct((P,), jnp.uint32),
        ]
    if cov is not None:
        out_shape.append(jax.ShapeDtypeStruct((cov.size,), jnp.int32))
    out_shape.append(jax.ShapeDtypeStruct((n_stats,), jnp.int32))

    scratch = [
        pltpu.VMEM(shape, dtype) for shape, dtype in cand_flat_shapes
    ]
    scratch += [pltpu.VMEM((B,), jnp.uint32) for _ in range(8)]
    scratch += [
        pltpu.VMEM((B,), jnp.int32),  # sidx
        pltpu.VMEM((F,), jnp.uint32),  # ebits_after
        pltpu.VMEM((F,), jnp.uint32),  # eval_mask
        pltpu.VMEM((F,), jnp.uint32),  # terminal
    ]
    if P:
        scratch.append(pltpu.VMEM((P, F), jnp.uint32))
    if cov is not None and P:
        scratch.append(pltpu.VMEM((P, F), jnp.uint32))
    scratch += [
        pltpu.SMEM((n_tiles + 1,), jnp.int32),  # per-tile key ranges
        pltpu.VMEM((_WIN, 2), jnp.uint32),  # window, even tiles
        pltpu.VMEM((_WIN, 2), jnp.uint32),  # window, odd tiles
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[vmem] * (n_state + 5 + n_const) + [any_],
        out_specs=[any_] + [vmem] * (len(out_shape) - 1),
        scratch_shapes=scratch,
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        # Table operand index counts the scalar-prefetch arg.
        input_output_aliases={1 + n_state + 5 + n_const: 0},
        compiler_params=_compiler_params(pltpu),
        interpret=spec.interpret,
    )(
        dcap.reshape((1,)),
        *state_leaves,
        hi,
        lo,
        ebits,
        depth,
        mask_u,
        *const_ops,
        table,
    )

    o = 0
    out_table = res[o]
    o += 1
    new_states = jax.tree_util.tree_unflatten(
        cand_tree, list(res[o : o + n_cand])
    )
    o += n_cand
    new_hi, new_lo, new_ebits, new_depth, parent_hi, parent_lo = res[
        o : o + 6
    ]
    o += 6
    if P:
        prop_hit, prop_hi, prop_lo = res[o : o + 3]
        o += 3
    if cov is not None:
        cov_vec = res[o]
        o += 1
    stats = res[o]

    out = {
        "table": out_table,
        "generated": stats[0],
        "n_new": stats[1],
        "overflow": stats[2],
        "max_depth": stats[3],
        "new": {
            "hi": new_hi,
            "lo": new_lo,
            "ebits": new_ebits,
            "depth": new_depth,
            "states": new_states,
        },
        "parent_hi": parent_hi,
        "parent_lo": parent_lo,
        "stats": stats,
    }
    if P:
        out["prop_hit"] = prop_hit != 0
        out["prop_hi"] = prop_hi
        out["prop_lo"] = prop_lo
    if cov is not None:
        out["cov"] = cov_vec
    return out
