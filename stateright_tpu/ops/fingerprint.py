"""Device-side 64-bit state fingerprinting over packed (array) states.

The host checkers hash a canonical byte encoding with blake2b
(``stateright_tpu.core.fingerprint``). On device, states are fixed-shape
pytrees of arrays; this module flattens them to a vector of uint32 words and
folds a murmur3-style mix over the words **twice with independent seeds**,
yielding a (hi, lo) pair of uint32 lanes = one 64-bit fingerprint.

Device fingerprints only need to be *stable within the device backend* — path
reconstruction replays the packed model and re-fingerprints with this same
function (reference requirement analog: fixed-seed ahash at
``/root/reference/src/lib.rs:357-375``). Fingerprints are kept as u32 pairs
(not u64) because TPUs have no native 64-bit integer path; all dedup
machinery sorts/compares lexicographically on (hi, lo).

The all-zero pair is reserved as the hash-set empty sentinel; fingerprints
are nudged to (0, 1) if they collide with it.

Kernel note: these functions are pure jnp word-mixing (no gather/scatter,
no host callbacks), so they trace cleanly *inside* Pallas kernels — the
fused wave megakernel (``ops/pallas_wave.py``) runs ``fingerprint_state``
over the candidate grid in its closure-converted prologue, and any model
``packed_fingerprint`` override must keep the same property to stay
fusable.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "state_words",
    "fingerprint_words",
    "fingerprint_state",
    "fp_to_int",
    "multiset_digest",
    "avalanche32",
    "component_seeds",
    "hash_rows",
    "combine_pairs",
    "pairs_acc",
    "acc_finalize",
    "multiset_row_pairs",
    "tenant_salt_pair",
    "salt_keys",
]

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_SEED_HI = 0x9747B28C
_SEED_LO = 0x3C6EF372


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _mm3_round(h: jax.Array, k: jax.Array) -> jax.Array:
    k = k * jnp.uint32(_C1)
    k = _rotl(k, 15)
    k = k * jnp.uint32(_C2)
    h = h ^ k
    h = _rotl(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h: jax.Array) -> jax.Array:
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def avalanche32(h: jax.Array) -> jax.Array:
    """Murmur3's fmix32: an invertible avalanche on uint32 lanes. Public
    because the checkers re-avalanche symmetry orbit-minimum keys with it
    (``checker/tpu._make_key_fn``) — any change here changes the visited-key
    space and MUST bump ``FP_SCHEME``."""
    return _fmix(h)


def _leaf_words(leaf: jax.Array) -> jax.Array:
    """A leaf of a single (unbatched) packed state as a 1-D uint32 vector."""
    x = jnp.asarray(leaf)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint32)
    elif x.dtype in (jnp.int8, jnp.uint8, jnp.int16, jnp.uint16):
        x = x.astype(jnp.uint32)
    elif x.dtype == jnp.int32:
        x = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype == jnp.float32:
        x = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype != jnp.uint32:
        raise TypeError(f"cannot fingerprint leaf dtype {x.dtype}")
    return x.reshape(-1)


def state_words(state: Any) -> jax.Array:
    """Flattens a single packed state pytree to its canonical uint32 words.

    The word layout is determined by the pytree structure, so two states of
    the same model always flatten identically. Unordered containers must be
    encoded canonically by the model itself (e.g. as bitmasks or sorted
    rows); arrays hash positionally.
    """
    leaves = jax.tree_util.tree_leaves(state)
    if not leaves:
        raise ValueError("packed state has no array leaves")
    return jnp.concatenate([_leaf_words(leaf) for leaf in leaves])


_CHUNKS = 16


def fingerprint_words(words: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo) uint32 fingerprint pair of a uint32 word vector.

    Word count must be static (it is, for fixed-shape packed states).
    Wide states (paxos packs 391 words, raft ~325) are hashed as
    ``_CHUNKS`` independent lanes — cutting the serial murmur chain's
    depth by that factor (the chain is the latency bottleneck of the
    per-candidate fingerprint; the VPU vectorizes across chunks exactly
    like it does across batch lanes) — then the chunk digests fold
    through a short unrolled chain. Zero-padding is safe: the word count
    is folded into the finalizer, and the chunk layout is static per
    shape.
    """
    n = words.shape[0]
    hi = jnp.uint32(_SEED_HI)
    lo = jnp.uint32(_SEED_LO)
    if n <= 64:
        # Unrolled: XLA fuses the whole fold into one elementwise chain.
        for i in range(n):
            w = words[i]
            hi = _mm3_round(hi, w)
            lo = _mm3_round(lo, w ^ jnp.uint32(0xA5A5A5A5))
    else:
        L = -(-n // _CHUNKS)
        padded = jnp.pad(words, (0, L * _CHUNKS - n)).reshape(_CHUNKS, L)
        lane = jnp.arange(_CHUNKS, dtype=jnp.uint32)
        chi = jnp.uint32(_SEED_HI) ^ (lane * jnp.uint32(0x9E3779B9))
        clo = jnp.uint32(_SEED_LO) ^ (lane * jnp.uint32(0x85EBCA6B))

        def body(carry, w):
            h, l = carry
            return (
                _mm3_round(h, w),
                _mm3_round(l, w ^ jnp.uint32(0xA5A5A5A5)),
            ), None

        (chi, clo), _ = jax.lax.scan(body, (chi, clo), padded.T)
        for k in range(_CHUNKS):
            hi = _mm3_round(hi, chi[k])
            lo = _mm3_round(lo, clo[k])
    return _finalize_pair(hi, lo, n)


def fingerprint_state(state: Any) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo) fingerprint of one packed state pytree. vmap over batches."""
    return fingerprint_words(state_words(state))


def multiset_row_pairs(rows: jax.Array):
    """Per-row (hi, lo) hashes exactly as ``multiset_digest`` folds them —
    exposed so incremental digest updates (add/remove one row's
    contribution) produce bit-identical algebra to the full digest. Same
    multilinear construction as ``hash_rows`` (one multiply + reduce, not
    a serial chain), under multiset-specific constant salts and seeds."""
    E, W = rows.shape
    khi = jnp.asarray(_lin_consts(W, 0x77A11 + 3 * W))
    klo = jnp.asarray(_lin_consts(W, 0x19D3F + 11 * W))
    acc_hi = (rows * khi[None, :]).sum(axis=1, dtype=jnp.uint32)
    acc_lo = (rows * klo[None, :]).sum(axis=1, dtype=jnp.uint32)
    hi = _fmix(acc_hi ^ jnp.uint32(_SEED_HI))
    lo = _fmix(acc_lo ^ jnp.uint32(_SEED_LO))
    return hi, lo


def multiset_digest(rows: jax.Array, active: jax.Array) -> jax.Array:
    """(4,) uint32 slot-order-insensitive digest of the active rows of a 2-D
    uint32 table: per-row murmur under two seeds (row-parallel — the serial
    chain is only W words long), combined by commutative reductions (sum and
    xor per seed lane). The device analog of the host's order-insensitive
    container hash (reference ``src/util.rs:137-159`` sorts element hashes;
    a commutative combine is the vmappable equivalent SURVEY §7 calls for).
    Models fold the digest into their fingerprint view instead of keeping
    unordered tables canonically sorted — removing per-transition and
    per-permutation sorts from the hot path."""
    hi, lo = multiset_row_pairs(rows)
    hi = jnp.where(active, hi, jnp.uint32(0))
    lo = jnp.where(active, lo, jnp.uint32(0))
    xor_hi = jax.lax.reduce(hi, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    xor_lo = jax.lax.reduce(lo, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    return jnp.stack(
        [hi.sum(dtype=jnp.uint32), xor_hi, lo.sum(dtype=jnp.uint32), xor_lo]
    )


def _finalize_pair(hi: jax.Array, lo: jax.Array, n: int):
    """Shared fmix + sentinel nudges: (0, 0) is the hash-set empty slot,
    (MAX, MAX) the checkers' invalid-lane sort sentinel."""
    hi = _fmix(hi ^ jnp.uint32(n * 4))
    lo = _fmix(lo ^ jnp.uint32(n * 4 + 1))
    m = jnp.uint32(0xFFFFFFFF)
    zero = (hi == 0) & (lo == 0)
    lo = jnp.where(zero, jnp.uint32(1), lo)
    maxed = (hi == m) & (lo == m)
    lo = jnp.where(maxed, m - 1, lo)
    return hi, lo


def component_seeds(tags: jax.Array):
    """Per-component seed pairs from integer component tags.

    The tag folds the component's *position* into its hash, so the
    component-wise state fingerprint stays positional even though each
    component is hashed independently: actor row 0 with content X and
    actor row 1 with content X produce different pairs.
    """
    t = jnp.asarray(tags, jnp.uint32)
    hi = _fmix(jnp.uint32(_SEED_HI) ^ (t * jnp.uint32(0x9E3779B9)))
    lo = _fmix(jnp.uint32(_SEED_LO) ^ (t * jnp.uint32(0x85EBCA6B)))
    return hi, lo


def _lin_consts(width: int, salt: int) -> "np.ndarray":
    """Deterministic odd uint32 coefficient vector for the multilinear row
    hash. Host-side ``RandomState`` (the frozen legacy generator — its bit
    stream is stability-guaranteed across numpy versions, which the
    fingerprint scheme requires across runs and checkpoints)."""
    import numpy as np

    rng = np.random.RandomState((0xC0FFEE ^ salt) & 0x7FFFFFFF)
    k = rng.randint(0, 1 << 32, size=width, dtype=np.uint32)
    return k | np.uint32(1)


def hash_rows(rows: jax.Array, tags: jax.Array):
    """(hi, lo) pairs of each row of a 2-D uint32 table, seeded per-row by
    ``tags`` — the component hash of the fingerprint scheme.

    Multilinear construction: ``fmix(Σ_j w_j · K_j  ⊕ tag_seed)`` with
    independent odd-constant vectors per lane. One multiply + one reduce
    over the row axis (a mat-vec XLA maps to the MXU on TPU; a handful of
    fused ops on CPU) instead of a W-step serial murmur chain — the chain
    was ~16 elementwise ops *per word*, which dominated both wall time and
    the op-level cost accounting at B-lane batch widths. Multilinear
    hashing over GF(2^32) with odd coefficients is a classic universal
    family (pairwise collision ≤ 2⁻³², squared across the two independent
    lanes); the fmix breaks linearity before pairs enter the cross-
    component accumulator. The incremental single-component rehash in
    ``PackedActorModel.packed_expand_fps`` calls this with one row; the
    direct fingerprint calls it with the whole table — identical by
    construction (same constants, same seeds)."""
    R, W = rows.shape
    khi = jnp.asarray(_lin_consts(W, 0x48AC1 + 2 * W))
    klo = jnp.asarray(_lin_consts(W, 0x5B3D5 + 7 * W))
    thi, tlo = component_seeds(tags)
    acc_hi = (rows * khi[None, :]).sum(axis=1, dtype=jnp.uint32)
    acc_lo = (rows * klo[None, :]).sum(axis=1, dtype=jnp.uint32)
    return _fmix(acc_hi ^ thi), _fmix(acc_lo ^ tlo)


def pairs_acc(his: jax.Array, los: jax.Array) -> jax.Array:
    """(4,) sum/xor accumulator over component-hash pairs. Commutative by
    construction, so a candidate's accumulator is the parent's plus the
    *changed* components' (new − old, xor-delta) contributions — O(1) per
    change, no per-candidate chain. Components must be distinct (each
    appears once); positionality lives in the tag-seeded pair hashes."""
    xor_hi = jax.lax.reduce(his, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    xor_lo = jax.lax.reduce(los, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    return jnp.stack(
        [his.sum(dtype=jnp.uint32), xor_hi, los.sum(dtype=jnp.uint32), xor_lo]
    )


def acc_finalize(acc: jax.Array, n_components: int):
    """State fingerprint from the component accumulator: both reductions
    (wrap-sum and xor) feed each output lane so neither algebra's
    collisions survive alone; fmix avalanches; sentinels reserved."""
    c = jnp.uint32(n_components)
    hi = _fmix(acc[0] ^ _rotl(acc[1], 16) ^ (c * jnp.uint32(0x9E3779B9)))
    lo = _fmix(acc[2] ^ _rotl(acc[3], 16) ^ (c * jnp.uint32(0x85EBCA6B) + 1))
    return _finalize_pair(hi, lo, n_components)


def combine_pairs(his: jax.Array, los: jax.Array):
    """One (hi, lo) state fingerprint from C component-hash pairs (the
    direct form of the accumulator scheme — ``pairs_acc`` + finalize)."""
    return acc_finalize(pairs_acc(his, los), his.shape[0])


# -- tenant salting (co-scheduled multi-tenancy; checker/packed_tenancy) ----
#
# Tenants packed into one shared visited table dedup on SALTED keys:
# ``(hi ^ salt_hi, lo ^ salt_lo)``. XOR is the whole trick — it is a
# bijection per tenant, so within a tenant two states collide salted iff
# they collide unsalted (the packed run's dedup behavior is bit-identical
# to the solo run's), while two tenants' keys relate through
# ``salt_a ^ salt_b``, an avalanche-mixed 64-bit constant, so cross-tenant
# aliasing is as (im)probable as any other 64-bit fingerprint collision.
# Unsalting is the same XOR, so host-side structures (parent logs,
# checkpoints, tiered-store partitions) always carry the tenant's ORIGINAL
# fingerprints.


def _fmix32_host(x: int) -> int:
    """Host-side murmur3 fmix32 (mirrors ``_fmix`` bit for bit)."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    return x ^ (x >> 16)


def tenant_salt_pair(epoch: int):
    """Deterministic (salt_hi, salt_lo) uint32 pair for tenant-salt epoch
    ``epoch``. fmix32 is a bijection on u32, so distinct epochs give
    distinct hi words — a re-admitted tenant under a fresh epoch can
    never dedup against a departed tenant's leftover table keys. Epoch 0
    is reserved for the identity salt (no-op: solo-compatible keys)."""
    if epoch == 0:
        return 0, 0
    hi = _fmix32_host(0x9E3779B9 * (2 * epoch + 1))
    lo = _fmix32_host(0x85EBCA6B * (2 * epoch + 3))
    # The identity salt is reserved; an (astronomically unlikely) fmix
    # collision with it just shifts to the neighbor epoch's mix.
    if hi == 0 and lo == 0:
        lo = 1
    return hi, lo


def salt_keys(hi: jax.Array, lo: jax.Array, salt_hi, salt_lo):
    """Applies per-lane XOR salts to (hi, lo) key lanes and re-nudges the
    reserved sentinels: (0, 0) is the hash-set empty slot and
    (MAX, MAX) the checkers' invalid-lane sentinel — a salted key landing
    on either must move off it (same nudge ``_finalize_pair`` applies to
    raw fingerprints; the salt map stays injective everywhere else)."""
    shi = hi ^ salt_hi
    slo = lo ^ salt_lo
    m = jnp.uint32(0xFFFFFFFF)
    zero = (shi == 0) & (slo == 0)
    slo = jnp.where(zero, jnp.uint32(1), slo)
    maxed = (shi == m) & (slo == m)
    slo = jnp.where(maxed, m - 1, slo)
    return shi, slo


def fp_to_int(hi, lo) -> int:
    """Host-side: a (hi, lo) pair as one python int fingerprint."""
    return (int(hi) << 32) | int(lo)


def fp64_pairs(hi, lo):
    """Host-side: (hi, lo) uint32 arrays combined into one uint64 array."""
    import numpy as np

    return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
        lo
    ).astype(np.uint64)


# Identifies the fingerprint definition (word layout + mixing, including the
# orbit-key avalanche in checker/tpu._make_key_fn). Checkpoints record it:
# visited-set keys and parent-store fps from a different scheme cannot be
# mixed into a resumed run. Bump on ANY change to the functions above, the
# orbit-key scramble, or a model's fingerprint view encoding.
FP_SCHEME = "linhash/comphash-v6"
