"""Device-side 64-bit state fingerprinting over packed (array) states.

The host checkers hash a canonical byte encoding with blake2b
(``stateright_tpu.core.fingerprint``). On device, states are fixed-shape
pytrees of arrays; this module flattens them to a vector of uint32 words and
folds a murmur3-style mix over the words **twice with independent seeds**,
yielding a (hi, lo) pair of uint32 lanes = one 64-bit fingerprint.

Device fingerprints only need to be *stable within the device backend* — path
reconstruction replays the packed model and re-fingerprints with this same
function (reference requirement analog: fixed-seed ahash at
``/root/reference/src/lib.rs:357-375``). Fingerprints are kept as u32 pairs
(not u64) because TPUs have no native 64-bit integer path; all dedup
machinery sorts/compares lexicographically on (hi, lo).

The all-zero pair is reserved as the hash-set empty sentinel; fingerprints
are nudged to (0, 1) if they collide with it.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "state_words",
    "fingerprint_words",
    "fingerprint_state",
    "fp_to_int",
    "multiset_digest",
    "avalanche32",
]

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_SEED_HI = 0x9747B28C
_SEED_LO = 0x3C6EF372


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _mm3_round(h: jax.Array, k: jax.Array) -> jax.Array:
    k = k * jnp.uint32(_C1)
    k = _rotl(k, 15)
    k = k * jnp.uint32(_C2)
    h = h ^ k
    h = _rotl(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h: jax.Array) -> jax.Array:
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def avalanche32(h: jax.Array) -> jax.Array:
    """Murmur3's fmix32: an invertible avalanche on uint32 lanes. Public
    because the checkers re-avalanche symmetry orbit-minimum keys with it
    (``checker/tpu._make_key_fn``) — any change here changes the visited-key
    space and MUST bump ``FP_SCHEME``."""
    return _fmix(h)


def _leaf_words(leaf: jax.Array) -> jax.Array:
    """A leaf of a single (unbatched) packed state as a 1-D uint32 vector."""
    x = jnp.asarray(leaf)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint32)
    elif x.dtype in (jnp.int8, jnp.uint8, jnp.int16, jnp.uint16):
        x = x.astype(jnp.uint32)
    elif x.dtype == jnp.int32:
        x = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype == jnp.float32:
        x = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype != jnp.uint32:
        raise TypeError(f"cannot fingerprint leaf dtype {x.dtype}")
    return x.reshape(-1)


def state_words(state: Any) -> jax.Array:
    """Flattens a single packed state pytree to its canonical uint32 words.

    The word layout is determined by the pytree structure, so two states of
    the same model always flatten identically. Unordered containers must be
    encoded canonically by the model itself (e.g. as bitmasks or sorted
    rows); arrays hash positionally.
    """
    leaves = jax.tree_util.tree_leaves(state)
    if not leaves:
        raise ValueError("packed state has no array leaves")
    return jnp.concatenate([_leaf_words(leaf) for leaf in leaves])


_CHUNKS = 16


def fingerprint_words(words: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo) uint32 fingerprint pair of a uint32 word vector.

    Word count must be static (it is, for fixed-shape packed states).
    Wide states (paxos packs 391 words, raft ~325) are hashed as
    ``_CHUNKS`` independent lanes — cutting the serial murmur chain's
    depth by that factor (the chain is the latency bottleneck of the
    per-candidate fingerprint; the VPU vectorizes across chunks exactly
    like it does across batch lanes) — then the chunk digests fold
    through a short unrolled chain. Zero-padding is safe: the word count
    is folded into the finalizer, and the chunk layout is static per
    shape.
    """
    n = words.shape[0]
    hi = jnp.uint32(_SEED_HI)
    lo = jnp.uint32(_SEED_LO)
    if n <= 64:
        # Unrolled: XLA fuses the whole fold into one elementwise chain.
        for i in range(n):
            w = words[i]
            hi = _mm3_round(hi, w)
            lo = _mm3_round(lo, w ^ jnp.uint32(0xA5A5A5A5))
    else:
        L = -(-n // _CHUNKS)
        padded = jnp.pad(words, (0, L * _CHUNKS - n)).reshape(_CHUNKS, L)
        lane = jnp.arange(_CHUNKS, dtype=jnp.uint32)
        chi = jnp.uint32(_SEED_HI) ^ (lane * jnp.uint32(0x9E3779B9))
        clo = jnp.uint32(_SEED_LO) ^ (lane * jnp.uint32(0x85EBCA6B))

        def body(carry, w):
            h, l = carry
            return (
                _mm3_round(h, w),
                _mm3_round(l, w ^ jnp.uint32(0xA5A5A5A5)),
            ), None

        (chi, clo), _ = jax.lax.scan(body, (chi, clo), padded.T)
        for k in range(_CHUNKS):
            hi = _mm3_round(hi, chi[k])
            lo = _mm3_round(lo, clo[k])
    hi = _fmix(hi ^ jnp.uint32(n * 4))
    lo = _fmix(lo ^ jnp.uint32(n * 4 + 1))
    # Reserve (0, 0) for the hash-set empty sentinel and (MAX, MAX) for the
    # checkers' invalid-lane sort sentinel.
    m = jnp.uint32(0xFFFFFFFF)
    zero = (hi == 0) & (lo == 0)
    lo = jnp.where(zero, jnp.uint32(1), lo)
    maxed = (hi == m) & (lo == m)
    lo = jnp.where(maxed, m - 1, lo)
    return hi, lo


def fingerprint_state(state: Any) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo) fingerprint of one packed state pytree. vmap over batches."""
    return fingerprint_words(state_words(state))


def multiset_digest(rows: jax.Array, active: jax.Array) -> jax.Array:
    """(4,) uint32 slot-order-insensitive digest of the active rows of a 2-D
    uint32 table: per-row murmur under two seeds (row-parallel — the serial
    chain is only W words long), combined by commutative reductions (sum and
    xor per seed lane). The device analog of the host's order-insensitive
    container hash (reference ``src/util.rs:137-159`` sorts element hashes;
    a commutative combine is the vmappable equivalent SURVEY §7 calls for).
    Models fold the digest into their fingerprint view instead of keeping
    unordered tables canonically sorted — removing per-transition and
    per-permutation sorts from the hot path."""
    E, W = rows.shape
    hi = jnp.full((E,), jnp.uint32(_SEED_HI))
    lo = jnp.full((E,), jnp.uint32(_SEED_LO))
    for w in range(W):
        col = rows[:, w]
        hi = _mm3_round(hi, col)
        lo = _mm3_round(lo, col ^ jnp.uint32(0xA5A5A5A5))
    hi = _fmix(hi ^ jnp.uint32(W * 4))
    lo = _fmix(lo ^ jnp.uint32(W * 4 + 1))
    hi = jnp.where(active, hi, jnp.uint32(0))
    lo = jnp.where(active, lo, jnp.uint32(0))
    xor_hi = jax.lax.reduce(hi, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    xor_lo = jax.lax.reduce(lo, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
    return jnp.stack(
        [hi.sum(dtype=jnp.uint32), xor_hi, lo.sum(dtype=jnp.uint32), xor_lo]
    )


def fp_to_int(hi, lo) -> int:
    """Host-side: a (hi, lo) pair as one python int fingerprint."""
    return (int(hi) << 32) | int(lo)


def fp64_pairs(hi, lo):
    """Host-side: (hi, lo) uint32 arrays combined into one uint64 array."""
    import numpy as np

    return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
        lo
    ).astype(np.uint64)


# Identifies the fingerprint definition (word layout + mixing, including the
# orbit-key avalanche in checker/tpu._make_key_fn). Checkpoints record it:
# visited-set keys and parent-store fps from a different scheme cannot be
# mixed into a resumed run. Bump on ANY change to the functions above, the
# orbit-key scramble, or a model's fingerprint view encoding.
FP_SCHEME = "mm3x2/msdigest-v4"
