"""Device-resident FIFO ring of frontier rows, shared by the deep drains.

Both ``TpuBfsChecker`` and ``ShardedTpuBfsChecker`` keep their pending
frontier in a fixed-capacity ring of packed-state rows living in device
memory: waves dequeue up to a frontier's width from the head and append
fresh rows at the tail, entirely inside the compiled loop. One
implementation of the wrap arithmetic (cumsum-compacted masked scatter on
push, masked gather on take, export-in-FIFO-order for growth and
checkpoints) keeps the two checkers in lockstep.

``capacity`` must be a power of two (callers size rings with
``_pow2ceil``); rows are dicts ``{states: pytree, hi, lo, ebits, depth}``
with a leading batch axis, plus a ``mask`` of valid lanes where noted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ring_rows", "ring_push", "ring_take", "ring_export"]

_ROW_KEYS = ("hi", "lo", "ebits", "depth")


def ring_rows(model, width: int):
    """Zeroed frontier-row storage of the given width for ``model``'s
    packed states."""
    init = model.packed_init_states()
    z = jnp.zeros((width,), jnp.uint32)
    return {
        "states": jax.tree_util.tree_map(
            lambda x: jnp.zeros((width,) + x.shape[1:], x.dtype), init
        ),
        "hi": z,
        "lo": z,
        "ebits": z,
        "depth": jnp.zeros((width,), jnp.int32),
    }


def ring_push(pool, head, count, rows, mask, capacity: int):
    """Appends ``rows``'s masked lanes at the ring tail (any mask pattern);
    returns ``(pool, count)``."""
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    dest = jnp.where(mask, (head + count + pos) & (capacity - 1), capacity)

    def scat(dst, src):
        return dst.at[dest].set(src, mode="drop")

    pool = {
        "states": jax.tree_util.tree_map(scat, pool["states"], rows["states"]),
        **{k: scat(pool[k], rows[k]) for k in _ROW_KEYS},
    }
    return pool, count + mask.sum(dtype=jnp.int32)


def ring_take(pool, head, count, capacity: int, width: int):
    """Dequeues up to ``width`` lanes from the ring head as a frontier
    (masked); returns ``(frontier, head, count)``."""
    lanes = jnp.arange(width, dtype=jnp.int32)
    take_n = jnp.minimum(count, width)
    idx = (head + lanes) & (capacity - 1)
    frontier = {
        "states": jax.tree_util.tree_map(lambda x: x[idx], pool["states"]),
        **{k: pool[k][idx] for k in _ROW_KEYS},
        "mask": lanes < take_n,
    }
    return frontier, (head + take_n) & (capacity - 1), count - take_n


def ring_export(pool, head, count, capacity: int):
    """The ring contents in FIFO order, padded to the full capacity with
    the valid-lane mask attached (for growth re-push and checkpoints)."""
    lanes = jnp.arange(capacity, dtype=jnp.int32)
    idx = (head + lanes) & (capacity - 1)
    return {
        "states": jax.tree_util.tree_map(lambda x: x[idx], pool["states"]),
        **{k: pool[k][idx] for k in _ROW_KEYS},
        "mask": lanes < count,
    }
