"""Pallas TPU kernel for the visited-set insert (tile-sweep open addressing).

The XLA path (``ops/hashset.py``) probes with per-round full-table gathers:
every probe round issues a B-lane random gather + scatter + re-read over the
whole HBM-resident table. This kernel exploits two structural facts the
checkers guarantee:

1. keys arrive **sorted** (the wave dedup sorts them), and
2. the home slot is **monotone in the key** (top bits of ``hi`` —
   ``ops/hashset._home``),

so the batch touches the table in a single left-to-right sweep. The kernel
grids over fixed-size table *tiles*; per tile it DMAs one window (tile +
``MAX_PROBES`` apron) HBM→VMEM, resolves every key homed in the tile against
VMEM (probe window compare + first-empty claim, sequentially per key — which
is exact CAS-free open addressing, since within one batch the keys are
processed in order), and DMAs the window back before the next tile starts.
Tiles no key homes into are skipped entirely — untouched rows never cross
HBM. Per-tile scalar ranges arrive via ``PrefetchScalarGridSpec`` from a
host-side ``searchsorted`` over the (monotone) homes.

Semantics match ``hashset_insert`` exactly (same contract, same claim/fresh/
found/pending outcomes) — property-tested against it in
``tests/test_pallas_hashset.py`` — EXCEPT that duplicate in-batch keys are
also handled (second occurrence reports ``found``), which is a superset of
the wave-unique contract.

Reference analog: the ``DashMap`` visited set at
``/root/reference/src/checker/bfs.rs:28-29``; SURVEY §7-5c calls for exactly
this "insert-heavy open-addressing in Pallas" design.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .hashset import MAX_PROBES

__all__ = [
    "pallas_hashset_insert",
    "probe_claim",
    "round_table_capacity",
    "TILE_ROWS",
]

# Table rows per grid step. 2048 rows × (2×4B) = 16KB window DMA (+ apron).
TILE_ROWS = 2048
# Keys resolved per inner chunk (bounds the per-chunk VMEM staging).
_KC = 256


def round_table_capacity(capacity: int) -> int:
    """The smallest power-of-two multiple of ``TILE_ROWS`` that holds
    ``capacity`` rows — the admissible table size for the tile-sweep
    kernels (``pallas_hashset_insert`` and the fused wave megakernel,
    ``ops/pallas_wave.py``), which grid over ``TILE_ROWS``-row table
    tiles. ``TILE_ROWS`` is itself a power of two, so every power of two
    at or above it is tile-aligned; callers report the adjustment
    instead of refusing admission."""
    c = max(int(capacity), TILE_ROWS)
    return 1 << (c - 1).bit_length()


def probe_claim(window, kh, kl, local):
    """Resolve one key against a VMEM table window: compare its
    ``MAX_PROBES``-row probe window at ``local``, claim the first empty
    slot when no match precedes it, and return ``(can_claim,
    is_found)``. Sequential per-key use makes the claim race-free — the
    next key observes this write in VMEM immediately, which is exact
    CAS-free open addressing. The claim is a masked whole-probe-window
    rewrite (a vector store — Mosaic handles dynamic scalar stores to
    VMEM poorly). Shared by the insert kernel below and the fused wave
    megakernel (``ops/pallas_wave.py``)."""
    from jax.experimental import pallas as pl

    rows_hi = window[pl.ds(local, MAX_PROBES), 0]
    rows_lo = window[pl.ds(local, MAX_PROBES), 1]
    idx = jax.lax.broadcasted_iota(
        jnp.int32, (MAX_PROBES, 1), 0
    ).reshape(MAX_PROBES)
    big = jnp.int32(MAX_PROBES)
    empty = (rows_hi == 0) & (rows_lo == 0)
    match = (rows_hi == kh) & (rows_lo == kl)
    first_empty = jnp.min(jnp.where(empty, idx, big))
    first_match = jnp.min(jnp.where(match, idx, big))
    is_found = first_match < first_empty
    can_claim = (first_empty < big) & ~is_found
    claim = can_claim & (idx == first_empty)
    window[pl.ds(local, MAX_PROBES), 0] = jnp.where(claim, kh, rows_hi)
    window[pl.ds(local, MAX_PROBES), 1] = jnp.where(claim, kl, rows_lo)
    return can_claim, is_found


def _insert_kernel(
    starts_ref,  # scalar-prefetch: (n_tiles + 1,) int32 key-range bounds
    cap_bits_ref,  # scalar-prefetch: (1,) int32 log2(capacity)
    key_hi_ref,  # VMEM (Bp,) uint32, sorted
    key_lo_ref,  # VMEM (Bp,) uint32
    active_ref,  # VMEM (Bp,) uint32 0/1
    table_ref,  # ANY/HBM (capacity + MAX_PROBES, 2) uint32, aliased output
    out_table_ref,  # alias of table_ref
    fresh_ref,  # VMEM (Bp,) uint32 out
    found_ref,  # VMEM (Bp,) uint32 out
    pending_ref,  # VMEM (Bp,) uint32 out
    window,  # VMEM scratch (TILE_ROWS + MAX_PROBES, 2) uint32
    sem_in,
    sem_out,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t = pl.program_id(0)
    s = starts_ref[t]
    e = starts_ref[t + 1]
    shift = 32 - cap_bits_ref[0]

    @pl.when(t == 0)
    def _zero_outputs():
        # Output buffers are uninitialized; lanes no tile writes (inactive
        # or sentinel keys) must still report all-false.
        fresh_ref[...] = jnp.zeros_like(fresh_ref)
        found_ref[...] = jnp.zeros_like(found_ref)
        pending_ref[...] = jnp.zeros_like(pending_ref)

    @pl.when(e > s)
    def _process_tile():
        base = t * TILE_ROWS
        dma_in = pltpu.make_async_copy(
            out_table_ref.at[pl.ds(base, TILE_ROWS + MAX_PROBES)],
            window,
            sem_in,
        )
        dma_in.start()
        dma_in.wait()

        def chunk_body(c, _):
            k0 = s + c * _KC

            def key_body(k, _):
                i = k0 + k

                @pl.when((i < e) & (active_ref[i] != 0))
                def _one_key():
                    kh = key_hi_ref[i]
                    kl = key_lo_ref[i]
                    local = (
                        (kh >> shift.astype(jnp.uint32)).astype(jnp.int32)
                        - base
                    )
                    can_claim, is_found = probe_claim(window, kh, kl, local)
                    fresh_ref[i] = can_claim.astype(jnp.uint32)
                    found_ref[i] = is_found.astype(jnp.uint32)
                    pending_ref[i] = (~is_found & ~can_claim).astype(
                        jnp.uint32
                    )

            jax.lax.fori_loop(0, _KC, key_body, None)
            return 0

        n_chunks = (e - s + _KC - 1) // _KC
        jax.lax.fori_loop(0, n_chunks, chunk_body, 0)

        dma_out = pltpu.make_async_copy(
            window,
            out_table_ref.at[pl.ds(base, TILE_ROWS + MAX_PROBES)],
            sem_out,
        )
        dma_out.start()
        dma_out.wait()


def _compiler_params(pltpu):
    """The ``has_side_effects`` compiler params across jax versions, by
    capability not name: jax >= 0.7 calls the class ``CompilerParams``,
    0.5/0.6 spell it ``TPUCompilerParams`` with the same field, and 0.4.x
    has neither field — there the legacy mosaic dict form carries it."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is not None:
        try:
            return cls(has_side_effects=True)
        except TypeError:
            pass
    return dict(mosaic=dict(has_side_effects=True))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_hashset_insert(
    table: jax.Array,
    key_hi: jax.Array,
    key_lo: jax.Array,
    active: jax.Array,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Drop-in for ``hashset_insert`` when keys are sorted ascending by
    (hi, lo). Returns ``(table, fresh, found, pending)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    capacity = table.shape[0] - MAX_PROBES
    cap_bits = capacity.bit_length() - 1
    assert capacity == (1 << cap_bits), "capacity must be a power of two"
    assert capacity % TILE_ROWS == 0, (
        f"capacity must be a multiple of TILE_ROWS={TILE_ROWS}"
    )
    n_tiles = capacity // TILE_ROWS
    B = key_hi.shape[0]

    # Host-side (XLA) prep: homes are monotone in the sorted keys, so each
    # tile's keys form a contiguous range found by searchsorted.
    homes = (key_hi >> jnp.uint32(32 - cap_bits)).astype(jnp.int32)
    # Inactive lanes must not extend ranges: sorted order puts the u32max
    # sentinels last; they map into the final tile and are masked by
    # ``active`` inside the kernel.
    bounds = jnp.arange(1, n_tiles + 1, dtype=jnp.int32) * TILE_ROWS
    starts = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            jnp.searchsorted(homes, bounds).astype(jnp.int32),
        ]
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((TILE_ROWS + MAX_PROBES, 2), jnp.uint32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    out_table, fresh, found, pending = pl.pallas_call(
        _insert_kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(table.shape, table.dtype),
            jax.ShapeDtypeStruct((B,), jnp.uint32),
            jax.ShapeDtypeStruct((B,), jnp.uint32),
            jax.ShapeDtypeStruct((B,), jnp.uint32),
        ),
        input_output_aliases={5: 0},  # table (arg idx incl. 2 prefetch args)
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(
        starts,
        jnp.full((1,), cap_bits, jnp.int32),
        key_hi,
        key_lo,
        active.astype(jnp.uint32),
        table,
    )
    return out_table, fresh != 0, found != 0, pending != 0
