"""Device-resident routing sieve for the sharded cross-shard exchange.

The sharded checker routes every candidate fingerprint to its owner shard
over ``lax.all_to_all`` each wave. Most of those candidates are re-visits:
the owner's hash set rejects them and the lane was shipped for nothing. The
sieve lets the *sender* drop lanes it can prove are already resident at
their owner, before the collective, without changing any result bit.

Two layers, maintained over the same key stream (every key this device has
routed since the last storage eviction):

1. **Receipt cache** — a direct-mapped table of ``2**slots_log2`` full
   ``(hi, lo)`` key pairs. A probe hit compares the *entire* key, so there
   are no false positives: a hit proves this device already routed exactly
   this key, hence the owner inserted it, hence the full-width exchange
   would have returned ``fresh=False`` for the lane. Dropping it is
   bit-identical by construction. Collisions simply overwrite (last writer
   wins); a stale miss only costs a redundant lane, never correctness.

2. **Bloom filter** — a byte-per-bit array summarizing the same routed
   keys. Bloom hits are *advisory only* (never drop a lane): the owner's
   insert verdict for a routed lane is an exact membership re-check, so
   ``bloom_hit & fresh`` counts true Bloom false positives with zero extra
   probes. This is the observed-FP audit that sizes the filter honestly
   (``comms.sieve.bloom_probe_total`` / ``bloom_fp_total``).

Both structures are flushed (zeroed) whenever the owner tables themselves
evict to host storage — after a flush, receipts only ever cover keys that
are still resident in device hash sets, which keeps even the out-of-core
per-lane fresh flags identical to the unsieved exchange.

All functions are pure jnp (gather/scatter + word mixing) and trace inside
``shard_map``; arrays are per-device (no replication of other shards'
state — the receipt cache summarizes *this device's own* routing history,
which is exactly the subset of the global visited set it can prove).

The all-zero key pair is the hash-set empty sentinel upstream
(``ops/fingerprint.py``) and doubles as the empty-slot sentinel here.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .fingerprint import avalanche32

__all__ = [
    "BLOOM_BITS_PER_KEY",
    "BLOOM_NUM_HASHES",
    "BLOOM_DESIGN_FP_RATE",
    "cache_new",
    "cache_probe",
    "cache_insert",
    "bloom_new",
    "bloom_bits_for",
    "bloom_probe",
    "bloom_insert",
]

# Same design point as the storage-tier run Blooms (storage/runs.py):
# 10 bits/key + 7 hashes => ~1% design false-positive rate at capacity.
BLOOM_BITS_PER_KEY = 10
BLOOM_NUM_HASHES = 7
BLOOM_DESIGN_FP_RATE = 0.01

_SALT_SLOT = jnp.uint32(0x9E3779B9)
_SALT_H1 = jnp.uint32(0x85EBCA6B)
_SALT_H2 = jnp.uint32(0xC2B2AE35)


def _fold(hi: jax.Array, lo: jax.Array, salt: jax.Array) -> jax.Array:
    """One avalanche over the 64-bit key folded with a lane salt."""
    return avalanche32(avalanche32(hi ^ salt) ^ lo)


def cache_new(slots_log2: int) -> jax.Array:
    """An empty receipt cache: ``(2**slots_log2, 2)`` uint32, all zero."""
    return jnp.zeros((1 << slots_log2, 2), jnp.uint32)


def _cache_slot(hi: jax.Array, lo: jax.Array, slots: int) -> jax.Array:
    return (_fold(hi, lo, _SALT_SLOT) & jnp.uint32(slots - 1)).astype(jnp.int32)


def cache_probe(
    cache: jax.Array, hi: jax.Array, lo: jax.Array, active: jax.Array
) -> jax.Array:
    """Exact membership of ``(hi, lo)`` in the receipt cache.

    Full-key compare: a ``True`` is a proof the key was routed (and hence is
    resident at its owner), never a hash coincidence. Inactive lanes return
    ``False``. The reserved (0, 0) pair never enters the key stream, so an
    empty slot cannot fake a hit.
    """
    slot = _cache_slot(hi, lo, cache.shape[0])
    return active & (cache[slot, 0] == hi) & (cache[slot, 1] == lo)


def cache_insert(
    cache: jax.Array, hi: jax.Array, lo: jax.Array, mask: jax.Array
) -> jax.Array:
    """Records masked lanes' keys; direct-mapped, colliders overwrite."""
    slot = _cache_slot(hi, lo, cache.shape[0])
    guarded = jnp.where(mask, slot, cache.shape[0])
    rows = jnp.stack([hi, lo], axis=-1)
    return cache.at[guarded].set(rows, mode="drop")


def bloom_bits_for(expected_keys: int) -> int:
    """Filter width (power of two, bits) for an expected key population."""
    want = max(64, expected_keys * BLOOM_BITS_PER_KEY)
    bits = 64
    while bits < want:
        bits <<= 1
    return bits


def bloom_new(bits: int) -> jax.Array:
    """An empty filter: one uint8 per bit (gather/scatter friendly)."""
    assert bits & (bits - 1) == 0, "bloom width must be a power of two"
    return jnp.zeros((bits,), jnp.uint8)


def _bloom_indices(hi: jax.Array, lo: jax.Array, bits: int) -> jax.Array:
    """(lanes, K) double-hashed probe positions: ``h1 + j*h2 (mod bits)``."""
    h1 = _fold(hi, lo, _SALT_H1)
    h2 = _fold(lo, hi, _SALT_H2) | jnp.uint32(1)  # odd => full-period stride
    j = jnp.arange(BLOOM_NUM_HASHES, dtype=jnp.uint32)
    idx = h1[..., None] + j * h2[..., None]
    return (idx & jnp.uint32(bits - 1)).astype(jnp.int32)


def bloom_probe(bloom: jax.Array, hi: jax.Array, lo: jax.Array) -> jax.Array:
    """``True`` iff all K probe bits are set (maybe-present)."""
    idx = _bloom_indices(hi, lo, bloom.shape[0])
    return jnp.all(bloom[idx] != 0, axis=-1)


def bloom_insert(
    bloom: jax.Array, hi: jax.Array, lo: jax.Array, mask: jax.Array
) -> jax.Array:
    """Sets the K bits for every masked lane."""
    idx = _bloom_indices(hi, lo, bloom.shape[0])
    guarded = jnp.where(mask[..., None], idx, bloom.shape[0])
    return bloom.at[guarded.reshape(-1)].set(jnp.uint8(1), mode="drop")
