"""Device-resident condition-false edge store + lasso-decision kernels.

The device checkers' parent-pointer log records TREE edges only, so it can
never answer "does the condition-false subgraph contain a cycle?" — the
question ``eventually`` soundness hangs on (``checker/liveness.py``). This
module is the missing edge relation and the decision procedure, both
device-native:

- **Edge log** (``edge_log_new`` / ``edge_log_append``): an append-only
  ring of (parent_fp, child_fp) u32-pair rows plus two u32 masks —
  ``emask`` (bit *b* set: both endpoints fail eventually-property *b*'s
  condition) and ``tmask`` (bit *b* set: the PARENT row is a terminal
  state failing property *b*; terminal rows carry a (0, 0) child
  sentinel, which no fingerprint can collide with). The append runs
  INSIDE the wave jit — one scatter per wave, no host exit — and the
  store is capacity-budgeted: the host evicts it to
  ``storage.LivenessEdgeStore`` (the PR 5 host-tier idiom) when a wave
  could overflow it.

- **Trim kernel** (``lasso_trim``): decides "a cycle exists among these
  edges" by iterated elimination of states with no outgoing edge — the
  GPUexplore-style whole-graph fixpoint ("On the Scalability of the
  GPUexplore Explicit-State Model Checker"). A non-empty fixed point ⟺ a
  cycle exists: every surviving node keeps an out-edge to a survivor, so
  survivors carry infinite paths, and a finite graph with one has a
  cycle. The naive peel is O(longest tail) rounds — fatal on chain-shaped
  regions (a 100K chain would peel one node per round) — so each round
  also CONTRACTS out-degree-1 chains with pointer doubling: ``f[v]`` =
  the unique successor (or ``v`` at branch/dead nodes), squared
  ``log2(N)`` times, lands every chain node on its chain's terminus; a
  dead terminus kills the whole chain in that one round. Rounds are thus
  bounded by the *branching* peel depth, and a pure cycle survives
  immediately (its pointers never reach a fixpoint, its out-degree never
  drops).

- **Reach kernel** (``reach_any``): frontier propagation from the
  condition-false roots with an any-candidate early exit — the
  restriction that keeps the verdict sound (a condition-false cycle
  hiding behind a condition-TRUE articulation state is NOT a
  counterexample; see ``checker/device_liveness.py``).

All three are pure jitted functions over padded power-of-two shapes so
the analysis pass compiles a handful of shapes, not one per model.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "edge_log_new",
    "edge_log_append",
    "lasso_trim",
    "reach_any",
    "EDGE_COLS",
]

# Columns of one edge-log row (struct-of-arrays, all uint32).
EDGE_COLS = ("phi", "plo", "chi", "clo", "emask", "tmask")


def edge_log_new(capacity: int) -> dict:
    """An empty device edge log: ``capacity`` rows of the six u32
    columns plus the device-resident fill count."""
    # One allocation per column — the checkers donate the whole dict
    # into the wave jits, and a shared zeros buffer would be the same
    # buffer donated six times.
    log = {c: jnp.zeros((capacity,), jnp.uint32) for c in EDGE_COLS}
    log["count"] = jnp.int32(0)
    return log


def edge_log_append(log: dict, rows: dict, n, capacity: int) -> dict:
    """Appends the first ``n`` rows of ``rows`` (prefix-compacted,
    same-length u32 columns) at the log's fill point. Runs inside the
    wave jit; rows past ``capacity`` drop (the host/drain guarantees
    headroom before dispatch — ``count`` still advances, so an
    overflow is detectable as ``count > capacity``)."""
    m = rows["phi"].shape[0]
    lanes = jnp.arange(m, dtype=jnp.int32)
    dest = jnp.where(lanes < n, log["count"] + lanes, capacity)
    out = {
        c: log[c].at[dest].set(rows[c], mode="drop") for c in EDGE_COLS
    }
    out["count"] = log["count"] + n
    return out


def _pow2ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def _seg_sums(active, values, starts):
    """Per-node segment reductions over src-sorted edges WITHOUT a
    scatter (XLA CPU scatters serialize; the cumsum-difference form is
    fully vectorized). ``starts`` is the CSR row-pointer array
    (int32[N+1] indices into the edge axis). Returns
    ``(count int32[N], wrapped_sum uint32[N])`` — the sum is modulo
    2^32 (uint32 cumsum wraparound), which recovers the EXACT single
    ``values`` entry whenever count == 1, the only case the trim
    consumes it."""
    csc = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(active.astype(jnp.int32))]
    )
    csd = jnp.concatenate(
        [
            jnp.zeros((1,), jnp.uint32),
            jnp.cumsum(
                jnp.where(active, values.astype(jnp.uint32), 0),
                dtype=jnp.uint32,
            ),
        ]
    )
    count = csc[starts[1:]] - csc[starts[:-1]]
    total = csd[starts[1:]] - csd[starts[:-1]]
    return count, total


@functools.partial(jax.jit, donate_argnums=())
def _trim_padded(src, dst, evalid, starts, nvalid):
    """Trim over padded CSR edges: ``src/dst`` int32[E] sorted by src
    (< N where valid), ``evalid`` bool[E] (padding rows False, so they
    contribute nothing to the segment cumsums wherever they sit),
    ``starts`` int32[N+1] row pointers, ``nvalid`` bool[N]. Returns the
    surviving-node mask and the round count."""
    N = nvalid.shape[0]
    iota = jnp.arange(N, dtype=jnp.int32)
    doublings = max(1, (N + 1).bit_length())

    def cond(c):
        alive, changed, _rounds = c
        return changed & alive.any()

    def body(c):
        alive, _changed, rounds = c
        ae = evalid & alive[src] & alive[dst]
        outdeg, usucc = _seg_sums(ae, dst, starts)
        f = jnp.where(outdeg == 1, usucc.astype(jnp.int32), iota)

        # Pointer doubling with early exit: most rounds' chains are
        # short (branch-heavy graphs contract in 1-2 squarings), so the
        # full log2(N) squarings would be pure waste. A pure f-cycle
        # never reaches a fixpoint and runs all of them — exactly the
        # case that must keep squaring (its terminus must land INSIDE
        # the cycle, where out-degree is 1, never 0).
        def dbl_cond(c):
            i, _f, changed = c
            return (i < doublings) & changed

        def dbl_body(c):
            i, g, _changed = c
            g2 = g[g]
            return i + 1, g2, (g2 != g).any()

        _i, f, _c = jax.lax.while_loop(
            dbl_cond, dbl_body, (jnp.int32(0), f, jnp.bool_(True))
        )
        # A node dies iff its out-degree-1 chain terminates at a node
        # with no outgoing edge (outdeg 0 includes the node itself when
        # it is already edge-less). Chains into a cycle never reach a
        # fixpoint and keep out-degree 1 — they survive, correctly.
        dead = outdeg[f] == 0
        alive2 = alive & ~dead
        return alive2, (alive2 != alive).any(), rounds + 1

    alive, _changed, rounds = jax.lax.while_loop(
        cond, body, (nvalid, jnp.bool_(True), jnp.int32(0))
    )
    return alive, rounds


def _csr(src, dst, evalid, n_nodes):
    """Host-side CSR prep shared by the kernels: sort edges by src,
    pad to power-of-two shapes (padding rows inactive), build the
    int32[Np+1] row pointers."""
    import numpy as np

    E = len(src)
    order = np.argsort(src, kind="stable")
    src_s = np.asarray(src, np.int32)[order]
    dst_s = np.asarray(dst, np.int32)[order]
    ev_s = np.asarray(evalid, bool)[order]
    Ep = max(8, _pow2ceil(E))
    Np = max(8, _pow2ceil(n_nodes))
    src_p = np.zeros((Ep,), np.int32)
    dst_p = np.zeros((Ep,), np.int32)
    ev_p = np.zeros((Ep,), bool)
    src_p[:E], dst_p[:E], ev_p[:E] = src_s, dst_s, ev_s
    starts = np.zeros((Np + 1,), np.int32)
    starts[1 : n_nodes + 1] = np.searchsorted(
        src_s, np.arange(1, n_nodes + 1)
    )
    starts[n_nodes + 1 :] = E
    return src_p, dst_p, ev_p, starts, Np


def lasso_trim(src, dst, evalid, nvalid) -> Tuple[jax.Array, jax.Array]:
    """Iterative condition-false trim (see module docstring). Inputs are
    numpy/JAX arrays in any edge order; they are CSR-sorted and padded
    to power-of-two shapes so repeated analyses share compiles. Returns
    ``(alive bool[N], rounds)`` sliced back to the caller's node
    count."""
    import numpy as np

    N = len(nvalid)
    src_p, dst_p, ev_p, starts, Np = _csr(src, dst, evalid, N)
    nv_p = np.zeros((Np,), bool)
    nv_p[:N] = nvalid
    alive, rounds = _trim_padded(
        jnp.asarray(src_p), jnp.asarray(dst_p), jnp.asarray(ev_p),
        jnp.asarray(starts), jnp.asarray(nv_p),
    )
    return np.asarray(alive)[:N], int(rounds)


@functools.partial(jax.jit, donate_argnums=())
def _reach_padded(src_r, dst_r, evalid_r, rstarts, roots, cand):
    """Frontier propagation over DST-sorted CSR edges: a node joins the
    reach set when any incoming edge's source is reached (segment-count
    over its incoming segment — scatter-free, like the trim)."""
    def cond(c):
        reach, changed, hit = c
        return changed & ~hit

    def body(c):
        reach, _changed, _hit = c
        ae = evalid_r & reach[src_r]
        indeg, _tot = _seg_sums(ae, dst_r, rstarts)
        reach2 = reach | (indeg > 0)
        return reach2, (reach2 != reach).any(), (reach2 & cand).any()

    reach0 = roots
    return jax.lax.while_loop(
        cond, body, (reach0, jnp.bool_(True), (reach0 & cand).any())
    )


def reach_any(src, dst, evalid, roots, cand):
    """Condition-false reachability from ``roots`` with an early exit
    the moment any ``cand`` node is reached. Returns ``(hit, reach)``
    (numpy), ``reach`` being the propagation fixpoint actually computed
    (exact when ``hit`` is False — the absence certificate)."""
    import numpy as np

    N = len(roots)
    # Reachability consumes INCOMING segments: build the CSR over dst.
    dst_p, src_p, ev_p, rstarts, Np = _csr(dst, src, evalid, N)
    r_p = np.zeros((Np,), bool)
    c_p = np.zeros((Np,), bool)
    r_p[:N], c_p[:N] = roots, cand
    reach, _changed, hit = _reach_padded(
        jnp.asarray(src_p), jnp.asarray(dst_p), jnp.asarray(ev_p),
        jnp.asarray(rstarts), jnp.asarray(r_p), jnp.asarray(c_p),
    )
    return bool(hit), np.asarray(reach)[:N]
