"""Micro-bench: XLA scatter-claim insert vs the Pallas tile-sweep kernel
on the current default device. Usage::

    python -m stateright_tpu.ops.bench_hashset [log2_capacity] [batch] [--json]

Feeds both paths identical sorted batches at the checkers' target load
factor and prints keys/sec for each. Decides whether runs should pass
``hashset_impl="pallas"`` to the TPU checkers (``checker/tpu.py`` — the
default stays "xla" until the Pallas path measures faster on hardware).
``--json`` prints ONE machine-readable line instead (recorded in
DEVICE_RUNS.jsonl by scripts/device_bench_run.sh so the per-backend
winner is part of the round's bench evidence).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def main():
    args = [a for a in sys.argv[1:] if a != "--json"]
    as_json = "--json" in sys.argv
    log2_cap = int(args[0]) if len(args) > 0 else 20
    batch = int(args[1]) if len(args) > 1 else 1 << 15
    cap = 1 << log2_cap
    rounds = max(1, int(cap * 0.5) // batch)  # fill to ~50% load

    from .hashset import hashset_insert, hashset_new
    from .pallas_hashset import pallas_hashset_insert

    dev = jax.devices()[0]
    interpret = dev.platform != "tpu"
    print(f"device={dev.platform} cap=2^{log2_cap} batch={batch} "
          f"rounds={rounds} interpret={interpret}", file=sys.stderr)

    rng = np.random.default_rng(0)

    def batches():
        for _ in range(rounds):
            hi = rng.integers(0, 1 << 32, batch, np.uint64).astype(np.uint32)
            lo = rng.integers(1, 1 << 32, batch, np.uint64).astype(np.uint32)
            order = np.lexsort((lo, hi))
            yield jnp.asarray(hi[order]), jnp.asarray(lo[order])

    ones = jnp.ones((batch,), bool)
    results = {}

    for name, fn in (
        ("xla", lambda t, h, l: hashset_insert(t, h, l, ones)),
        (
            "pallas",
            lambda t, h, l: pallas_hashset_insert(
                t, h, l, ones, interpret=interpret
            ),
        ),
    ):
        data = list(batches())
        table = hashset_new(cap)
        # Warm up compile on the first batch shape.
        out = fn(table, *data[0])
        jax.block_until_ready(out[0])
        table = hashset_new(cap)
        t0 = time.perf_counter()
        lanes = 0
        fresh_total = jnp.zeros((), jnp.int32)
        pend_total = jnp.zeros((), jnp.int32)
        for h, l in data:
            table, fresh, _found, pend = fn(table, h, l)
            lanes += batch
            fresh_total = fresh_total + fresh.sum(dtype=jnp.int32)
            pend_total = pend_total + pend.sum(dtype=jnp.int32)
        jax.block_until_ready(table)
        dt = time.perf_counter() - t0
        fresh_n = int(fresh_total)
        results[name] = {
            "lanes_per_s": round(lanes / dt, 1),
            "inserts_per_s": round(fresh_n / dt, 1),
            "fresh": fresh_n,
            "pending": int(pend_total),
        }
        out_line = (
            f"{name}: {lanes} lanes in {dt:.3f}s = {lanes/dt:,.0f} lanes/s, "
            f"{fresh_n/dt:,.0f} effective inserts/s "
            f"(fresh={fresh_n} pending={int(pend_total)})"
        )
        print(out_line, file=sys.stderr if as_json else sys.stdout)

    if as_json:
        print(
            json.dumps(
                {
                    "device": dev.platform,
                    "compiled": not interpret,
                    "cap_log2": log2_cap,
                    "batch": batch,
                    **results,
                    "winner": max(
                        results, key=lambda k: results[k]["lanes_per_s"]
                    ),
                }
            )
        )


if __name__ == "__main__":
    main()
