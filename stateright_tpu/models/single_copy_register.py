"""Single-copy (non-replicated) register servers — linearizable with one
server (93 states for 2 clients), NOT linearizable with two.

Reference: ``/root/reference/examples/single-copy-register.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..actor import Actor, ActorModel, Id, Network, Out
from ..actor.register import (
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

DEFAULT_VALUE = "\x00"


class SingleCopyActor(Actor):
    def on_start(self, id: Id, o: Out) -> str:
        return DEFAULT_VALUE

    def on_msg(self, id: Id, state: str, src: Id, msg, o: Out):
        if isinstance(msg, Put):
            o.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            # Writing the same state back still counts as a write in the
            # reference (send side effect makes this a non-no-op anyway).
            return None
        return None


@dataclass
class SingleCopyModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        )
        for _ in range(self.server_count):
            model.actor(SingleCopyActor())
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
