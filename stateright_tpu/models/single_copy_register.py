"""Single-copy (non-replicated) register servers — linearizable with one
server (93 states for 2 clients), NOT linearizable with two.

Reference: ``/root/reference/examples/single-copy-register.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..actor import Actor, ActorModel, Id, Network, Out
from ..actor.packed import PackedActorModel
from ..actor import packed_register as pr
from ..actor.register import (
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

DEFAULT_VALUE = "\x00"


class SingleCopyActor(Actor):
    def on_start(self, id: Id, o: Out) -> str:
        return DEFAULT_VALUE

    def on_msg(self, id: Id, state: str, src: Id, msg, o: Out):
        if isinstance(msg, Put):
            o.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            # Writing the same state back still counts as a write in the
            # reference (send side effect makes this a non-no-op anyway).
            return None
        return None


class SingleCopyPackedCodec(pr.RegisterProtocolCodec):
    """Packed kernels for the single-copy server + register clients.
    Server row ``[val, 0, 0]``; messages are the shared register kinds
    (``W = 3``: ``[kind, req, val]``)."""

    msg_width = 3
    state_width = pr.CLIENT_ROW_WORDS

    def __init__(self, client_count: int, server_count: int):
        self.send_capacity = 1
        self._init_register_protocol(client_count, server_count, DEFAULT_VALUE)

    def pack_actor_state(self, i, s) -> np.ndarray:
        if i >= self.server_count:
            return pr.pack_client_state(s, self.state_width)
        row = np.zeros((self.state_width,), np.uint32)
        row[0] = ord(s)
        return row

    def unpack_actor_state(self, i, row):
        if i >= self.server_count:
            return pr.unpack_client_state(row)
        return chr(np.asarray(row)[0])

    def pack_msg(self, msg) -> np.ndarray:
        vec = np.zeros((self.msg_width,), np.uint32)
        if isinstance(msg, Put):
            vec[:] = [pr.K_PUT, msg.request_id, ord(msg.value)]
        elif isinstance(msg, Get):
            vec[:2] = [pr.K_GET, msg.request_id]
        elif isinstance(msg, PutOk):
            vec[:2] = [pr.K_PUT_OK, msg.request_id]
        elif isinstance(msg, GetOk):
            vec[:] = [pr.K_GET_OK, msg.request_id, ord(msg.value)]
        else:
            raise TypeError(f"cannot pack message: {msg!r}")
        return vec

    def unpack_msg(self, vec):
        vec = np.asarray(vec)
        k = int(vec[0])
        if k == pr.K_PUT:
            return Put(int(vec[1]), chr(vec[2]))
        if k == pr.K_GET:
            return Get(int(vec[1]))
        if k == pr.K_PUT_OK:
            return PutOk(int(vec[1]))
        if k == pr.K_GET_OK:
            return GetOk(int(vec[1]), chr(vec[2]))
        raise ValueError(f"unknown packed message kind: {k}")

    def on_msg_branches(self, model):
        import jax.numpy as jnp

        u = jnp.uint32
        W = self.msg_width

        def server_on_msg(me, row, src, msg):
            kind, req = msg[0], msg[1]
            srcu = src.astype(u)
            z = u(0)
            ns = jnp.full((1, 1 + W), self.SEND_NONE)
            is_put = kind == u(pr.K_PUT)
            is_get = kind == u(pr.K_GET)
            put_send = jnp.stack([srcu, u(pr.K_PUT_OK), req, z])
            get_send = jnp.stack([srcu, u(pr.K_GET_OK), req, row[0]])
            sends = jnp.where(
                is_put,
                ns.at[0].set(put_send),
                jnp.where(is_get, ns.at[0].set(get_send), ns),
            )
            row_out = row.at[0].set(jnp.where(is_put, msg[2], row[0]))
            return row_out, sends, z, z, is_put

        client = pr.client_on_msg_branch(self, self.put_count, self.server_count)
        return [server_on_msg, client]


@dataclass
class SingleCopyModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )
    envelope_capacity: int = 8

    def into_model(self) -> ActorModel:
        model = PackedActorModel(
            codec=SingleCopyPackedCodec(self.client_count, self.server_count),
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        ).with_envelope_capacity(self.envelope_capacity)
        if self.network.kind == "ordered":
            # Same structural restriction as AbdModelCfg: register clients
            # never message clients, nobody messages itself. flow depth 2
            # is a PHASE-TOTAL bound here (provably safe, not just
            # measured): a single-copy client sends exactly two messages
            # per server pair over its whole life (Put then Get, the Get
            # only after PutOk) and the server sends exactly the two
            # replies — a FIFO can never hold more than was ever sent.
            model = model.with_flow_pairs(
                pr.register_flow_pairs(self.client_count, self.server_count)
            ).with_flow_capacity(2)
        for _ in range(self.server_count):
            model.actor(SingleCopyActor())
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
