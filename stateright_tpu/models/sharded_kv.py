"""Sharded key-value store with live key migration.

The ROADMAP 6(b) "too big to enumerate" zoo entry: ``K`` keys spread
over ``S`` shards, clients writing (bounded version counters), and a
migration protocol that hands a key from its owner to a destination
shard in two steps (``MigrateStart`` marks the key in flight,
``MigrateComplete`` transfers ownership). The modeled bug — the swarm
bench's known violation — is a write landing while the key's handoff is
in flight: with ``guarded=False`` (the default) writes are accepted
during migration and mark the key *torn* (the update can land on the
old owner after the new owner took over), violating ``always "no torn
writes"``. ``guarded=True`` refuses writes on in-flight keys, the fix.

State-space scale: roughly ``S^K · (V+1)^K · (S+1)^K · 2^K`` upper
bound. The parity config (S=2, K=2, V=1) is a few hundred reachable
states — host/device equivalence is testable; the bench config
(S=4, K=8, V=3) is ~10^14, far beyond the tiered store — the swarm's
territory.

Properties:
- ``always "no torn writes"`` (antecedent: some migration in flight —
  the coverage ledger flags a run that never exercised migration as a
  vacuous pass). Violated when ``guarded=False`` at depth 2 (shallow).
- ``always "no total tear"`` — EVERY key torn at once: the deep
  violation (>= 2·K actions from init). At bench scale (K=8) the
  breadth-first frontier explodes long before that depth, while a
  random walk reaches it in one trace — the swarm-vs-exhaustive
  time-to-first-violation leg.
- ``sometimes "fully migrated"`` — every key left its home shard.
- ``sometimes "saturated writes"`` — every key's version hit the cap.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Model, Property

# ``inflight`` sentinel: no migration for this key.
_NONE = None


@dataclass(frozen=True)
class ShardedKvState:
    owner: Tuple[int, ...]       # key -> owning shard
    ver: Tuple[int, ...]         # key -> version counter
    inflight: Tuple[Optional[int], ...]  # key -> destination shard | None
    torn: Tuple[bool, ...]       # key -> a write raced its migration


class ShardedKv(Model, BatchableModel):
    """``S`` shards, ``K`` keys (home shard ``k % S``), versions bounded
    by ``V``. ``guarded=True`` is the fixed protocol (no writes while a
    key is in flight)."""

    def __init__(self, shards: int = 2, keys: int = 2, max_version: int = 1,
                 guarded: bool = False, retain=None):
        if shards < 2:
            raise ValueError("migration needs at least 2 shards")
        self.S = int(shards)
        self.K = int(keys)
        self.V = int(max_version)
        self.guarded = bool(guarded)
        # Optional property filter (the actor models' retain_properties
        # analog): keeps properties/conditions/antecedents aligned, so
        # a bench leg can time exactly one violation hunt.
        self._retain = (
            tuple(retain)
            if retain is not None and not isinstance(retain, str)
            else ((retain,) if retain else None)
        )

    def _keep(self, items, props):
        if self._retain is None:
            return items
        kept = [
            x for p, x in zip(props, items) if p.name in self._retain
        ]
        if len(kept) != len(self._retain):
            have = [p.name for p in props]
            raise ValueError(
                f"retain={self._retain!r} does not match properties "
                f"{have!r}"
            )
        return kept

    def _home(self, k: int) -> int:
        return k % self.S

    # -- host model ---------------------------------------------------------

    def init_states(self) -> List[ShardedKvState]:
        return [
            ShardedKvState(
                owner=tuple(self._home(k) for k in range(self.K)),
                ver=(0,) * self.K,
                inflight=(_NONE,) * self.K,
                torn=(False,) * self.K,
            )
        ]

    def actions(self, state: ShardedKvState, actions: List) -> None:
        for k in range(self.K):
            if state.ver[k] < self.V and (
                not self.guarded or state.inflight[k] is _NONE
            ):
                actions.append(("Write", k))
            if state.inflight[k] is _NONE:
                for d in range(self.S):
                    if d != state.owner[k]:
                        actions.append(("MigrateStart", k, d))
            else:
                actions.append(("MigrateComplete", k))

    def next_state(self, state: ShardedKvState, action) -> ShardedKvState:
        kind, k = action[0], action[1]
        owner = list(state.owner)
        ver = list(state.ver)
        inflight = list(state.inflight)
        torn = list(state.torn)
        if kind == "Write":
            ver[k] += 1
            if inflight[k] is not _NONE:
                # The race: an accepted write while the key is mid-
                # handoff can land on the retiring owner and vanish.
                torn[k] = True
        elif kind == "MigrateStart":
            inflight[k] = action[2]
        elif kind == "MigrateComplete":
            owner[k] = inflight[k]
            inflight[k] = _NONE
        else:
            raise ValueError(f"unknown action {action!r}")
        return ShardedKvState(
            owner=tuple(owner), ver=tuple(ver),
            inflight=tuple(inflight), torn=tuple(torn),
        )

    def _all_properties(self) -> List[Property]:
        return [
            Property.always(
                "no torn writes",
                lambda _, s: not any(s.torn),
                antecedent=lambda _, s: any(
                    f is not _NONE for f in s.inflight
                ),
            ),
            # The DEEP violation (swarm bench territory): every key
            # torn at once sits >= 2K actions from init — beyond any
            # breadth-first horizon at bench scale, trivial for a
            # depth-first random walk.
            Property.always(
                "no total tear",
                lambda _, s: not all(s.torn),
                antecedent=lambda _, s: any(
                    f is not _NONE for f in s.inflight
                ),
            ),
            Property.sometimes(
                "fully migrated",
                lambda m, s: all(
                    s.owner[k] != m._home(k) for k in range(m.K)
                ),
            ),
            Property.sometimes(
                "saturated writes",
                lambda m, s: all(v == m.V for v in s.ver),
            ),
        ]

    def properties(self) -> List[Property]:
        props = self._all_properties()
        return self._keep(props, props)

    # -- BatchableModel (packed protocol) -----------------------------------
    #
    # Packed layout (all uint32, length-K vectors):
    #   owner:    key -> owning shard
    #   ver:      key -> version
    #   inflight: key -> destination shard, S = none
    #   torn:     key -> 0/1
    #
    # Dense action ids (A = K + K*S + K):
    #   [0, K)           Write(k = aid)
    #   [K, K + K*S)     MigrateStart(k = (aid-K) // S, d = (aid-K) % S)
    #   [K + K*S, A)     MigrateComplete(k = aid - K - K*S)

    def packed_action_count(self) -> int:
        return self.K * (self.S + 2)

    def packed_action_labels(self):
        labels = [f"Write_{k}" for k in range(self.K)]
        for k in range(self.K):
            labels += [
                f"MigrateStart_{k}_to_{d}" for d in range(self.S)
            ]
        labels += [f"MigrateComplete_{k}" for k in range(self.K)]
        return labels

    def packed_init_states(self):
        import jax.numpy as jnp

        K = self.K
        return {
            "owner": jnp.asarray(
                [[self._home(k) for k in range(K)]], jnp.uint32
            ),
            "ver": jnp.zeros((1, K), jnp.uint32),
            "inflight": jnp.full((1, K), self.S, jnp.uint32),
            "torn": jnp.zeros((1, K), jnp.uint32),
        }

    def packed_step(self, state, action_id):
        import jax.numpy as jnp

        K, S = self.K, self.S
        aid = action_id.astype(jnp.int32)
        is_write = aid < K
        is_start = (aid >= K) & (aid < K + K * S)
        k = jnp.where(
            is_write,
            aid,
            jnp.where(is_start, (aid - K) // S, aid - K - K * S),
        )
        k = jnp.clip(k, 0, K - 1)
        d = jnp.clip((aid - K) % S, 0, S - 1).astype(jnp.uint32)

        owner, ver = state["owner"], state["ver"]
        inflight, torn = state["inflight"], state["torn"]
        none = jnp.uint32(S)
        key_free = inflight[k] == none
        valid = jnp.where(
            is_write,
            (ver[k] < jnp.uint32(self.V))
            & (jnp.bool_(not self.guarded) | key_free),
            jnp.where(
                is_start,
                key_free & (d != owner[k]),
                ~key_free,
            ),
        )

        onehot = jnp.arange(K) == k
        new_ver = jnp.where(
            onehot & is_write, ver + jnp.uint32(1), ver
        ).astype(jnp.uint32)
        new_torn = jnp.where(
            onehot & is_write & ~key_free, jnp.uint32(1), torn
        ).astype(jnp.uint32)
        new_inflight = jnp.where(
            onehot & is_start,
            d,
            jnp.where(onehot & ~is_write & ~is_start, none, inflight),
        ).astype(jnp.uint32)
        new_owner = jnp.where(
            onehot & ~is_write & ~is_start, inflight[k], owner
        ).astype(jnp.uint32)
        return {
            "owner": new_owner,
            "ver": new_ver,
            "inflight": new_inflight,
            "torn": new_torn,
        }, valid

    def packed_conditions(self):
        import jax.numpy as jnp

        home = jnp.asarray(
            [self._home(k) for k in range(self.K)], jnp.uint32
        )
        conds = [
            lambda st: ~(st["torn"] == 1).any(),
            lambda st: ~(st["torn"] == 1).all(),
            lambda st, h=home: (st["owner"] != h).all(),
            lambda st: (st["ver"] == jnp.uint32(self.V)).all(),
        ]
        return self._keep(conds, self._all_properties())

    def packed_antecedents(self):
        import jax.numpy as jnp

        def inflight_any(st):
            return (st["inflight"] != jnp.uint32(self.S)).any()

        return self._keep(
            [inflight_any, inflight_any, None, None],
            self._all_properties(),
        )

    def pack_state(self, host_state: ShardedKvState):
        return {
            "owner": np.asarray(host_state.owner, np.uint32),
            "ver": np.asarray(host_state.ver, np.uint32),
            "inflight": np.asarray(
                [
                    self.S if f is _NONE else f
                    for f in host_state.inflight
                ],
                np.uint32,
            ),
            "torn": np.asarray(
                [1 if t else 0 for t in host_state.torn], np.uint32
            ),
        }

    def unpack_state(self, packed) -> ShardedKvState:
        inflight = tuple(
            _NONE if int(f) == self.S else int(f)
            for f in np.asarray(packed["inflight"])
        )
        return ShardedKvState(
            owner=tuple(int(o) for o in np.asarray(packed["owner"])),
            ver=tuple(int(v) for v in np.asarray(packed["ver"])),
            inflight=inflight,
            torn=tuple(bool(t) for t in np.asarray(packed["torn"])),
        )
