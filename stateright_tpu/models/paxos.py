"""Single Decree Paxos, checked for linearizability against a register spec.

Two clients / three servers under an unordered non-duplicating network reach
exactly 16,668 unique states (the primary throughput benchmark config). The
model is a ``PackedActorModel``: the same actors check on the host engines
AND stage onto the device checkers, auxiliary linearizability history
included (bounded-width encoding + interleaving-table predicate — see
``semantics/packed_linearizability.py``).

Reference: ``/root/reference/examples/paxos.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from ..actor import Actor, ActorModel, Id, Network, Out, model_peers
from ..actor.packed import PackedActorModel
from ..actor import packed_register as pr
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

DEFAULT_VALUE = "\x00"  # the register's initial value (reference: char::default)


def majority(cluster_size: int) -> int:
    """The minimum size of a majority within a cluster."""
    return cluster_size // 2 + 1


# Internal protocol messages are tagged tuples:
#   ("Prepare", ballot)
#   ("Prepared", ballot, last_accepted)
#   ("Accept", ballot, proposal)
#   ("Accepted", ballot)
#   ("Decided", ballot, proposal)
# ballot = (round, leader_id); proposal = (request_id, requester_id, value);
# last_accepted/accepted = None | (ballot, proposal).


def _accepted_sort_key(accepted):
    # None sorts below any accepted (ballot, proposal), like Rust's Option.
    return (0,) if accepted is None else (1, accepted)


@dataclass(frozen=True)
class PaxosState:
    # shared state
    ballot: Tuple[int, int]
    # leader state
    proposal: Optional[Tuple]
    prepares: Tuple  # sorted tuple of (acceptor_id, last_accepted)
    accepts: FrozenSet[Id]
    # acceptor state
    accepted: Optional[Tuple]
    is_decided: bool


class PaxosActor(Actor):
    def __init__(self, peer_ids: List[Id]):
        self.peer_ids = peer_ids

    def name(self) -> str:
        return "Paxos Server"

    def on_start(self, id: Id, o: Out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state: PaxosState, src: Id, msg, o: Out):
        if state.is_decided:
            if isinstance(msg, Get):
                # Reply with the decided value (never reply "undecided": a
                # value may have been decided elsewhere with delivery pending).
                _b, (_req_id, _src, value) = state.accepted
                o.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, id)
            proposal = (msg.request_id, src, msg.value)
            # Simulate Prepare + Prepared self-sends.
            prepares = ((id, state.accepted),)
            o.broadcast(self.peer_ids, Internal(("Prepare", ballot)))
            return PaxosState(
                ballot=ballot,
                proposal=proposal,
                prepares=prepares,
                accepts=frozenset(),
                accepted=state.accepted,
                is_decided=False,
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            kind = inner[0]
            if kind == "Prepare" and state.ballot < inner[1]:
                ballot = inner[1]
                o.send(
                    src, Internal(("Prepared", ballot, state.accepted))
                )
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=state.accepted,
                    is_decided=False,
                )
            if kind == "Prepared" and inner[1] == state.ballot:
                ballot, last_accepted = inner[1], inner[2]
                prepares_map = dict(state.prepares)
                prepares_map[src] = last_accepted
                prepares = tuple(sorted(prepares_map.items()))
                proposal = state.proposal
                accepted = state.accepted
                accepts = state.accepts
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # Leadership handoff: favor the most recently accepted
                    # proposal from the prepare quorum; else the client's.
                    best = max(
                        prepares_map.values(), key=_accepted_sort_key
                    )
                    proposal = best[1] if best is not None else state.proposal
                    # Simulate Accept + Accepted self-sends.
                    accepted = (ballot, proposal)
                    accepts = frozenset([id])
                    o.broadcast(
                        self.peer_ids, Internal(("Accept", ballot, proposal))
                    )
                return PaxosState(
                    ballot=state.ballot,
                    proposal=proposal,
                    prepares=prepares,
                    accepts=accepts,
                    accepted=accepted,
                    is_decided=False,
                )
            if kind == "Accept" and state.ballot <= inner[1]:
                ballot, proposal = inner[1], inner[2]
                o.send(src, Internal(("Accepted", ballot)))
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(ballot, proposal),
                    is_decided=False,
                )
            if kind == "Accepted" and inner[1] == state.ballot:
                ballot = inner[1]
                accepts = state.accepts | {src}
                is_decided = state.is_decided
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    is_decided = True
                    proposal = state.proposal
                    o.broadcast(
                        self.peer_ids, Internal(("Decided", ballot, proposal))
                    )
                    request_id, requester_id, _ = proposal
                    o.send(requester_id, PutOk(request_id))
                return PaxosState(
                    ballot=state.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=accepts,
                    accepted=state.accepted,
                    is_decided=is_decided,
                )
            if kind == "Decided":
                ballot, proposal = inner[1], inner[2]
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(ballot, proposal),
                    is_decided=True,
                )
        return None


class PaxosPackedCodec(pr.RegisterProtocolCodec):
    """Packed kernels for ``PaxosActor`` + ``RegisterClient`` + the
    linearizability history — the traceable twin of the host callbacks above.

    Server row (``R = 14 + 7*Ns`` u32 words):
    ``[b_rnd, b_ldr, has_prop, p_req, p_rqr, p_val, is_decided,
    has_acc, a_rnd, a_ldr, a_req, a_rqr, a_val, accepts_mask,
    then per server s: [present, has_la, la_rnd, la_ldr, la_req, la_rqr,
    la_val]]``. Client rows use the shared register layout (padded).

    Messages (``W = 9``): register kinds 1-4 (``packed_register``), then
    Prepare=5 ``[k, rnd, ldr]``, Prepared=6 ``[k, rnd, ldr, has_la, la_rnd,
    la_ldr, la_req, la_rqr, la_val]``, Accept=7 ``[k, rnd, ldr, req, rqr,
    val]``, Accepted=8 ``[k, rnd, ldr]``, Decided=9 (Accept layout).
    """

    K_PREPARE = pr.KIND_INTERNAL_BASE
    K_PREPARED = pr.KIND_INTERNAL_BASE + 1
    K_ACCEPT = pr.KIND_INTERNAL_BASE + 2
    K_ACCEPTED = pr.KIND_INTERNAL_BASE + 3
    K_DECIDED = pr.KIND_INTERNAL_BASE + 4

    msg_width = 9

    def __init__(self, client_count: int, server_count: int):
        self.state_width = 14 + 7 * server_count
        self.send_capacity = server_count
        self._init_register_protocol(client_count, server_count, DEFAULT_VALUE)

    # -- host <-> packed ---------------------------------------------------

    def pack_actor_state(self, i, s) -> np.ndarray:
        row = np.zeros((self.state_width,), np.uint32)
        if i >= self.server_count:
            return pr.pack_client_state(s, self.state_width)
        row[0], row[1] = s.ballot[0], int(s.ballot[1])
        if s.proposal is not None:
            row[2] = 1
            row[3], row[4], row[5] = (
                s.proposal[0],
                int(s.proposal[1]),
                ord(s.proposal[2]),
            )
        row[6] = 1 if s.is_decided else 0
        if s.accepted is not None:
            (rnd, ldr), (req, rqr, val) = s.accepted
            row[7:13] = [1, rnd, int(ldr), req, int(rqr), ord(val)]
        for v in s.accepts:
            row[13] |= np.uint32(1) << np.uint32(int(v))
        for acceptor, la in s.prepares:
            b = 14 + 7 * int(acceptor)
            row[b] = 1
            if la is not None:
                (rnd, ldr), (req, rqr, val) = la
                row[b + 1 : b + 7] = [1, rnd, int(ldr), req, int(rqr), ord(val)]
        return row

    def unpack_actor_state(self, i, row):
        if i >= self.server_count:
            return pr.unpack_client_state(row)
        row = np.asarray(row)

        def opt_bp(base):  # Option<(ballot, proposal)> at 6 words
            if not row[base]:
                return None
            return (
                (int(row[base + 1]), Id(int(row[base + 2]))),
                (int(row[base + 3]), Id(int(row[base + 4])), chr(row[base + 5])),
            )

        prepares = []
        for s in range(self.server_count):
            b = 14 + 7 * s
            if row[b]:
                prepares.append((Id(s), opt_bp(b + 1)))
        return PaxosState(
            ballot=(int(row[0]), Id(int(row[1]))),
            proposal=(
                (int(row[3]), Id(int(row[4])), chr(row[5]))
                if row[2]
                else None
            ),
            prepares=tuple(prepares),
            accepts=frozenset(
                Id(b)
                for b in range(self.server_count)
                if int(row[13]) & (1 << b)
            ),
            accepted=opt_bp(7),
            is_decided=bool(row[6]),
        )

    def pack_msg(self, msg) -> np.ndarray:
        vec = np.zeros((self.msg_width,), np.uint32)

        def put_bp(base, bp):  # (ballot, proposal) pair, no presence flag
            (rnd, ldr), (req, rqr, val) = bp
            vec[base : base + 5] = [rnd, int(ldr), req, int(rqr), ord(val)]

        if isinstance(msg, Put):
            vec[0], vec[1], vec[2] = pr.K_PUT, msg.request_id, ord(msg.value)
        elif isinstance(msg, Get):
            vec[0], vec[1] = pr.K_GET, msg.request_id
        elif isinstance(msg, PutOk):
            vec[0], vec[1] = pr.K_PUT_OK, msg.request_id
        elif isinstance(msg, GetOk):
            vec[0], vec[1], vec[2] = (
                pr.K_GET_OK,
                msg.request_id,
                ord(msg.value),
            )
        elif isinstance(msg, Internal):
            inner = msg.msg
            kind = inner[0]
            if kind == "Prepare":
                vec[0], vec[1], vec[2] = self.K_PREPARE, inner[1][0], int(inner[1][1])
            elif kind == "Prepared":
                vec[0], vec[1], vec[2] = self.K_PREPARED, inner[1][0], int(inner[1][1])
                if inner[2] is not None:
                    vec[3] = 1
                    (rnd, ldr), (req, rqr, val) = inner[2]
                    vec[4:9] = [rnd, int(ldr), req, int(rqr), ord(val)]
            elif kind == "Accept":
                vec[0], vec[1], vec[2] = self.K_ACCEPT, inner[1][0], int(inner[1][1])
                req, rqr, val = inner[2]
                vec[3:6] = [req, int(rqr), ord(val)]
            elif kind == "Accepted":
                vec[0], vec[1], vec[2] = self.K_ACCEPTED, inner[1][0], int(inner[1][1])
            elif kind == "Decided":
                vec[0], vec[1], vec[2] = self.K_DECIDED, inner[1][0], int(inner[1][1])
                req, rqr, val = inner[2]
                vec[3:6] = [req, int(rqr), ord(val)]
            else:
                raise ValueError(f"unknown internal message: {inner!r}")
        else:
            raise TypeError(f"cannot pack message: {msg!r}")
        return vec

    def unpack_msg(self, vec):
        vec = np.asarray(vec)
        k = int(vec[0])
        if k == pr.K_PUT:
            return Put(int(vec[1]), chr(vec[2]))
        if k == pr.K_GET:
            return Get(int(vec[1]))
        if k == pr.K_PUT_OK:
            return PutOk(int(vec[1]))
        if k == pr.K_GET_OK:
            return GetOk(int(vec[1]), chr(vec[2]))
        ballot = (int(vec[1]), Id(int(vec[2])))
        if k == self.K_PREPARE:
            return Internal(("Prepare", ballot))
        if k == self.K_PREPARED:
            la = None
            if vec[3]:
                la = (
                    (int(vec[4]), Id(int(vec[5]))),
                    (int(vec[6]), Id(int(vec[7])), chr(vec[8])),
                )
            return Internal(("Prepared", ballot, la))
        prop = (int(vec[3]), Id(int(vec[4])), chr(vec[5]))
        if k == self.K_ACCEPT:
            return Internal(("Accept", ballot, prop))
        if k == self.K_ACCEPTED:
            return Internal(("Accepted", ballot))
        if k == self.K_DECIDED:
            return Internal(("Decided", ballot, prop))
        raise ValueError(f"unknown packed message kind: {k}")

    # -- traceable kernels -------------------------------------------------

    def on_msg_branches(self, model):
        import jax
        import jax.numpy as jnp

        u = jnp.uint32
        Ns = self.server_count
        maj = majority(Ns)
        no_sends, send_row, broadcast = pr.trace_helpers(self, Ns)

        def lex_gt(a, b):
            """a > b over equal-length u32 key vectors (static unroll)."""
            gt = jnp.bool_(False)
            eq = jnp.bool_(True)
            for k in range(a.shape[0]):
                gt = gt | (eq & (a[k] > b[k]))
                eq = eq & (a[k] == b[k])
            return gt

        def server_on_msg(me, row, src, msg):
            kind = msg[0]
            meu = me.astype(u)
            srcu = src.astype(u)
            z = u(0)
            ns = no_sends()
            b_rnd, b_ldr = row[0], row[1]
            has_prop = row[2]
            decided = row[6]
            accepts = row[13]
            mb_rnd, mb_ldr = msg[1], msg[2]

            # ---- decided: Get gets the decided value, all else ignored ----
            dec_get = kind == u(pr.K_GET)
            dec_sends = jnp.where(
                dec_get,
                ns.at[0].set(
                    send_row(srcu, u(pr.K_GET_OK), msg[1], row[12])
                ),
                ns,
            )

            # ---- Put (no proposal yet): start a new ballot ----------------
            put_fire = (kind == u(pr.K_PUT)) & (has_prop == 0)
            nb_rnd = b_rnd + 1
            put_row = (
                row.at[0].set(nb_rnd).at[1].set(meu)
                .at[2].set(u(1)).at[3].set(msg[1]).at[4].set(srcu)
                .at[5].set(msg[2]).at[13].set(z)
            )
            own_prep = jnp.concatenate([jnp.ones((1,), u), row[7:13]])
            for s in range(Ns):
                b = 14 + 7 * s
                ent = jnp.where(u(s) == meu, own_prep, jnp.zeros((7,), u))
                put_row = put_row.at[b : b + 7].set(ent)
            put_sends = broadcast(meu, u(self.K_PREPARE), nb_rnd, meu)

            # ---- Prepare (msg ballot beats ours): adopt + answer ----------
            b_lt = (b_rnd < mb_rnd) | ((b_rnd == mb_rnd) & (b_ldr < mb_ldr))
            b_eq = (b_rnd == mb_rnd) & (b_ldr == mb_ldr)
            prep_fire = (kind == u(self.K_PREPARE)) & b_lt
            prep_row = row.at[0].set(mb_rnd).at[1].set(mb_ldr)
            prep_sends = ns.at[0].set(
                send_row(
                    srcu, u(self.K_PREPARED), mb_rnd, mb_ldr,
                    row[7], row[8], row[9], row[10], row[11], row[12],
                )
            )

            # ---- Prepared (for our current ballot) ------------------------
            pred_fire = (kind == u(self.K_PREPARED)) & b_eq
            la_ent = jnp.stack(
                [u(1), msg[3], msg[4], msg[5], msg[6], msg[7], msg[8]]
            )
            pred_row = row
            for s in range(Ns):
                b = 14 + 7 * s
                pred_row = pred_row.at[b : b + 7].set(
                    jnp.where(srcu == u(s), la_ent, pred_row[b : b + 7])
                )
            count = z
            for s in range(Ns):
                count = count + pred_row[14 + 7 * s]
            quorum = count == u(maj)
            # Leadership handoff: max last_accepted over present prepares
            # (leading present bit keeps absent entries from winning).
            best = pred_row[14 : 14 + 7]
            for s in range(1, Ns):
                ent = pred_row[14 + 7 * s : 14 + 7 * s + 7]
                best = jnp.where(lex_gt(ent, best), ent, best)
            best_has_la = best[1] == 1
            q_req = jnp.where(best_has_la, best[4], row[3])
            q_rqr = jnp.where(best_has_la, best[5], row[4])
            q_val = jnp.where(best_has_la, best[6], row[5])
            q_has = jnp.where(best_has_la, u(1), has_prop)
            q_row = (
                pred_row.at[2].set(q_has).at[3].set(q_req).at[4].set(q_rqr)
                .at[5].set(q_val)
                .at[7].set(u(1)).at[8].set(mb_rnd).at[9].set(mb_ldr)
                .at[10].set(q_req).at[11].set(q_rqr).at[12].set(q_val)
                .at[13].set(u(1) << meu)
            )
            q_sends = broadcast(
                meu, u(self.K_ACCEPT), mb_rnd, mb_ldr, q_req, q_rqr, q_val
            )
            pred_row = jnp.where(quorum, q_row, pred_row)
            pred_sends = jnp.where(quorum, q_sends, ns)

            # ---- Accept (ballot at or beyond ours): adopt + ack -----------
            acc_fire = (kind == u(self.K_ACCEPT)) & (b_lt | b_eq)
            acc_row = (
                row.at[0].set(mb_rnd).at[1].set(mb_ldr)
                .at[7].set(u(1)).at[8].set(mb_rnd).at[9].set(mb_ldr)
                .at[10].set(msg[3]).at[11].set(msg[4]).at[12].set(msg[5])
            )
            acc_sends = ns.at[0].set(
                send_row(srcu, u(self.K_ACCEPTED), mb_rnd, mb_ldr)
            )

            # ---- Accepted (for our ballot): count the quorum --------------
            actd_fire = (kind == u(self.K_ACCEPTED)) & b_eq
            accepts2 = accepts | (u(1) << srcu)
            dec_quorum = jax.lax.population_count(accepts2) == u(maj)
            actd_row = row.at[13].set(accepts2)
            actd_row = actd_row.at[6].set(
                jnp.where(dec_quorum, u(1), decided)
            )
            dec_bcast = broadcast(
                meu, u(self.K_DECIDED), b_rnd, b_ldr, row[3], row[4], row[5]
            )
            dec_bcast = dec_bcast.at[me].set(
                send_row(row[4], u(pr.K_PUT_OK), row[3])
            )
            actd_sends = jnp.where(dec_quorum, dec_bcast, ns)

            # ---- Decided: adopt unconditionally ---------------------------
            decd_fire = kind == u(self.K_DECIDED)
            decd_row = (
                row.at[0].set(mb_rnd).at[1].set(mb_ldr)
                .at[7].set(u(1)).at[8].set(mb_rnd).at[9].set(mb_ldr)
                .at[10].set(msg[3]).at[11].set(msg[4]).at[12].set(msg[5])
                .at[6].set(u(1))
            )

            # ---- select (kinds are mutually exclusive) --------------------
            row_out = row
            sends = ns
            for fire, r, sd in (
                (put_fire, put_row, put_sends),
                (prep_fire, prep_row, prep_sends),
                (pred_fire, pred_row, pred_sends),
                (acc_fire, acc_row, acc_sends),
                (actd_fire, actd_row, actd_sends),
                (decd_fire, decd_row, ns),
            ):
                row_out = jnp.where(fire, r, row_out)
                sends = jnp.where(fire, sd, sends)
            changed = (
                put_fire | prep_fire | pred_fire | acc_fire | actd_fire
                | decd_fire
            )
            is_dec = decided == 1
            row_out = jnp.where(is_dec, row, row_out)
            sends = jnp.where(is_dec, dec_sends, sends)
            changed = jnp.where(is_dec, jnp.bool_(False), changed)
            return row_out, sends, z, z, changed

        client = pr.client_on_msg_branch(self, self.put_count, Ns)
        return [server_on_msg, client]


@dataclass
class PaxosModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )
    envelope_capacity: int = 16

    def into_model(self) -> ActorModel:
        model = PackedActorModel(
            codec=PaxosPackedCodec(self.client_count, self.server_count),
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        ).with_envelope_capacity(self.envelope_capacity)
        for i in range(self.server_count):
            model.actor(PaxosActor(model_peers(i, self.server_count)))
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
