"""Single Decree Paxos, checked for linearizability against a register spec.

Two clients / three servers under an unordered non-duplicating network reach
exactly 16,668 unique states (the primary throughput benchmark config).

Reference: ``/root/reference/examples/paxos.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, Out, model_peers
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

DEFAULT_VALUE = "\x00"  # the register's initial value (reference: char::default)


def majority(cluster_size: int) -> int:
    """The minimum size of a majority within a cluster."""
    return cluster_size // 2 + 1


# Internal protocol messages are tagged tuples:
#   ("Prepare", ballot)
#   ("Prepared", ballot, last_accepted)
#   ("Accept", ballot, proposal)
#   ("Accepted", ballot)
#   ("Decided", ballot, proposal)
# ballot = (round, leader_id); proposal = (request_id, requester_id, value);
# last_accepted/accepted = None | (ballot, proposal).


def _accepted_sort_key(accepted):
    # None sorts below any accepted (ballot, proposal), like Rust's Option.
    return (0,) if accepted is None else (1, accepted)


@dataclass(frozen=True)
class PaxosState:
    # shared state
    ballot: Tuple[int, int]
    # leader state
    proposal: Optional[Tuple]
    prepares: Tuple  # sorted tuple of (acceptor_id, last_accepted)
    accepts: FrozenSet[Id]
    # acceptor state
    accepted: Optional[Tuple]
    is_decided: bool


class PaxosActor(Actor):
    def __init__(self, peer_ids: List[Id]):
        self.peer_ids = peer_ids

    def name(self) -> str:
        return "Paxos Server"

    def on_start(self, id: Id, o: Out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state: PaxosState, src: Id, msg, o: Out):
        if state.is_decided:
            if isinstance(msg, Get):
                # Reply with the decided value (never reply "undecided": a
                # value may have been decided elsewhere with delivery pending).
                _b, (_req_id, _src, value) = state.accepted
                o.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, id)
            proposal = (msg.request_id, src, msg.value)
            # Simulate Prepare + Prepared self-sends.
            prepares = ((id, state.accepted),)
            o.broadcast(self.peer_ids, Internal(("Prepare", ballot)))
            return PaxosState(
                ballot=ballot,
                proposal=proposal,
                prepares=prepares,
                accepts=frozenset(),
                accepted=state.accepted,
                is_decided=False,
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            kind = inner[0]
            if kind == "Prepare" and state.ballot < inner[1]:
                ballot = inner[1]
                o.send(
                    src, Internal(("Prepared", ballot, state.accepted))
                )
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=state.accepted,
                    is_decided=False,
                )
            if kind == "Prepared" and inner[1] == state.ballot:
                ballot, last_accepted = inner[1], inner[2]
                prepares_map = dict(state.prepares)
                prepares_map[src] = last_accepted
                prepares = tuple(sorted(prepares_map.items()))
                proposal = state.proposal
                accepted = state.accepted
                accepts = state.accepts
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # Leadership handoff: favor the most recently accepted
                    # proposal from the prepare quorum; else the client's.
                    best = max(
                        prepares_map.values(), key=_accepted_sort_key
                    )
                    proposal = best[1] if best is not None else state.proposal
                    # Simulate Accept + Accepted self-sends.
                    accepted = (ballot, proposal)
                    accepts = frozenset([id])
                    o.broadcast(
                        self.peer_ids, Internal(("Accept", ballot, proposal))
                    )
                return PaxosState(
                    ballot=state.ballot,
                    proposal=proposal,
                    prepares=prepares,
                    accepts=accepts,
                    accepted=accepted,
                    is_decided=False,
                )
            if kind == "Accept" and state.ballot <= inner[1]:
                ballot, proposal = inner[1], inner[2]
                o.send(src, Internal(("Accepted", ballot)))
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(ballot, proposal),
                    is_decided=False,
                )
            if kind == "Accepted" and inner[1] == state.ballot:
                ballot = inner[1]
                accepts = state.accepts | {src}
                is_decided = state.is_decided
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    is_decided = True
                    proposal = state.proposal
                    o.broadcast(
                        self.peer_ids, Internal(("Decided", ballot, proposal))
                    )
                    request_id, requester_id, _ = proposal
                    o.send(requester_id, PutOk(request_id))
                return PaxosState(
                    ballot=state.ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=accepts,
                    accepted=state.accepted,
                    is_decided=is_decided,
                )
            if kind == "Decided":
                ballot, proposal = inner[1], inner[2]
                return PaxosState(
                    ballot=ballot,
                    proposal=state.proposal,
                    prepares=state.prepares,
                    accepts=state.accepts,
                    accepted=(ballot, proposal),
                    is_decided=True,
                )
        return None


@dataclass
class PaxosModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        )
        for i in range(self.server_count):
            model.actor(PaxosActor(model_peers(i, self.server_count)))
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
