"""ABD quorum register (Attiya, Bar-Noy, Dolev) — a replicated register that
IS linearizable without consensus. 2 clients / 2 servers = 544 unique states.

Internal protocol (tagged tuples inside ``Internal``):
  ("Query", req_id)
  ("AckQuery", req_id, seq, val)
  ("Record", req_id, seq, val)
  ("AckRecord", req_id)
where seq = (logical_clock, actor_id).

Reference: ``/root/reference/examples/linearizable-register.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, Out, model_peers
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register
from .paxos import majority

DEFAULT_VALUE = "\x00"


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[str]  # Some(value) for Put, None for Get
    responses: Tuple  # sorted tuple of (actor_id, (seq, val))


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    read: Optional[str]  # Some(value) for Get, None for Put
    acks: Tuple  # sorted tuple of actor ids


@dataclass(frozen=True)
class AbdState:
    seq: Tuple[int, int]
    val: str
    phase: object  # None | Phase1 | Phase2


class AbdActor(Actor):
    def __init__(self, peers: List[Id]):
        self.peers = peers

    def on_start(self, id: Id, o: Out) -> AbdState:
        return AbdState(seq=(0, id), val=DEFAULT_VALUE, phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg, o: Out):
        if isinstance(msg, Put) and state.phase is None:
            o.broadcast(self.peers, Internal(("Query", msg.request_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=msg.value,
                    responses=((id, (state.seq, state.val)),),
                ),
            )
        if isinstance(msg, Get) and state.phase is None:
            o.broadcast(self.peers, Internal(("Query", msg.request_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=None,
                    responses=((id, (state.seq, state.val)),),
                ),
            )
        if not isinstance(msg, Internal):
            return None
        inner = msg.msg
        kind = inner[0]

        if kind == "Query":
            o.send(src, Internal(("AckQuery", inner[1], state.seq, state.val)))
            return None

        if (
            kind == "AckQuery"
            and isinstance(state.phase, Phase1)
            and state.phase.request_id == inner[1]
        ):
            _req, seq_in, val_in = inner[1], inner[2], inner[3]
            phase = state.phase
            responses = dict(phase.responses)
            responses[src] = (seq_in, val_in)
            if len(responses) == majority(len(self.peers) + 1):
                # Quorum reached; move to phase 2. Sequencers are distinct, so
                # max-by-seq is deterministic.
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                read = None
                if phase.write is not None:
                    seq = (seq[0] + 1, id)
                    val = phase.write
                else:
                    read = val
                o.broadcast(
                    self.peers, Internal(("Record", phase.request_id, seq, val))
                )
                # Self-send Record.
                new_seq, new_val = state.seq, state.val
                if seq > state.seq:
                    new_seq, new_val = seq, val
                # Self-send AckRecord.
                return AbdState(
                    seq=new_seq,
                    val=new_val,
                    phase=Phase2(
                        request_id=phase.request_id,
                        requester_id=phase.requester_id,
                        read=read,
                        acks=(id,),
                    ),
                )
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=phase.request_id,
                    requester_id=phase.requester_id,
                    write=phase.write,
                    responses=tuple(sorted(responses.items())),
                ),
            )

        if kind == "Record":
            _req, seq_in, val_in = inner[1], inner[2], inner[3]
            o.send(src, Internal(("AckRecord", inner[1])))
            if seq_in > state.seq:
                return AbdState(seq=seq_in, val=val_in, phase=state.phase)
            return None

        if (
            kind == "AckRecord"
            and isinstance(state.phase, Phase2)
            and state.phase.request_id == inner[1]
            and src not in state.phase.acks
        ):
            phase = state.phase
            acks = tuple(sorted(set(phase.acks) | {src}))
            if len(acks) == majority(len(self.peers) + 1):
                if phase.read is not None:
                    o.send(
                        phase.requester_id, GetOk(phase.request_id, phase.read)
                    )
                else:
                    o.send(phase.requester_id, PutOk(phase.request_id))
                return AbdState(seq=state.seq, val=state.val, phase=None)
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase2(
                    request_id=phase.request_id,
                    requester_id=phase.requester_id,
                    read=phase.read,
                    acks=acks,
                ),
            )
        return None


@dataclass
class AbdModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        model = ActorModel(
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        )
        for i in range(self.server_count):
            model.actor(AbdActor(model_peers(i, self.server_count)))
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
