"""ABD quorum register (Attiya, Bar-Noy, Dolev) — a replicated register that
IS linearizable without consensus. 2 clients / 2 servers = 544 unique states.

Internal protocol (tagged tuples inside ``Internal``):
  ("Query", req_id)
  ("AckQuery", req_id, seq, val)
  ("Record", req_id, seq, val)
  ("AckRecord", req_id)
where seq = (logical_clock, actor_id).

Reference: ``/root/reference/examples/linearizable-register.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..actor import Actor, ActorModel, Id, Network, Out, model_peers
from ..actor.packed import PackedActorModel
from ..actor import packed_register as pr
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register
from .paxos import majority

DEFAULT_VALUE = "\x00"


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[str]  # Some(value) for Put, None for Get
    responses: Tuple  # sorted tuple of (actor_id, (seq, val))


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    read: Optional[str]  # Some(value) for Get, None for Put
    acks: Tuple  # sorted tuple of actor ids


@dataclass(frozen=True)
class AbdState:
    seq: Tuple[int, int]
    val: str
    phase: object  # None | Phase1 | Phase2


class AbdActor(Actor):
    def __init__(self, peers: List[Id]):
        self.peers = peers

    def on_start(self, id: Id, o: Out) -> AbdState:
        return AbdState(seq=(0, id), val=DEFAULT_VALUE, phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg, o: Out):
        if isinstance(msg, Put) and state.phase is None:
            o.broadcast(self.peers, Internal(("Query", msg.request_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=msg.value,
                    responses=((id, (state.seq, state.val)),),
                ),
            )
        if isinstance(msg, Get) and state.phase is None:
            o.broadcast(self.peers, Internal(("Query", msg.request_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=None,
                    responses=((id, (state.seq, state.val)),),
                ),
            )
        if not isinstance(msg, Internal):
            return None
        inner = msg.msg
        kind = inner[0]

        if kind == "Query":
            o.send(src, Internal(("AckQuery", inner[1], state.seq, state.val)))
            return None

        if (
            kind == "AckQuery"
            and isinstance(state.phase, Phase1)
            and state.phase.request_id == inner[1]
        ):
            _req, seq_in, val_in = inner[1], inner[2], inner[3]
            phase = state.phase
            responses = dict(phase.responses)
            responses[src] = (seq_in, val_in)
            if len(responses) == majority(len(self.peers) + 1):
                # Quorum reached; move to phase 2. Sequencers are distinct, so
                # max-by-seq is deterministic.
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                read = None
                if phase.write is not None:
                    seq = (seq[0] + 1, id)
                    val = phase.write
                else:
                    read = val
                o.broadcast(
                    self.peers, Internal(("Record", phase.request_id, seq, val))
                )
                # Self-send Record.
                new_seq, new_val = state.seq, state.val
                if seq > state.seq:
                    new_seq, new_val = seq, val
                # Self-send AckRecord.
                return AbdState(
                    seq=new_seq,
                    val=new_val,
                    phase=Phase2(
                        request_id=phase.request_id,
                        requester_id=phase.requester_id,
                        read=read,
                        acks=(id,),
                    ),
                )
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=phase.request_id,
                    requester_id=phase.requester_id,
                    write=phase.write,
                    responses=tuple(sorted(responses.items())),
                ),
            )

        if kind == "Record":
            _req, seq_in, val_in = inner[1], inner[2], inner[3]
            o.send(src, Internal(("AckRecord", inner[1])))
            if seq_in > state.seq:
                return AbdState(seq=seq_in, val=val_in, phase=state.phase)
            return None

        if (
            kind == "AckRecord"
            and isinstance(state.phase, Phase2)
            and state.phase.request_id == inner[1]
            and src not in state.phase.acks
        ):
            phase = state.phase
            acks = tuple(sorted(set(phase.acks) | {src}))
            if len(acks) == majority(len(self.peers) + 1):
                if phase.read is not None:
                    o.send(
                        phase.requester_id, GetOk(phase.request_id, phase.read)
                    )
                else:
                    o.send(phase.requester_id, PutOk(phase.request_id))
                return AbdState(seq=state.seq, val=state.val, phase=None)
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase2(
                    request_id=phase.request_id,
                    requester_id=phase.requester_id,
                    read=phase.read,
                    acks=acks,
                ),
            )
        return None


class AbdPackedCodec(pr.RegisterProtocolCodec):
    """Packed kernels for ``AbdActor`` + ``RegisterClient`` + history.

    Server row (``R = 9 + 4*Ns``):
    ``[seq_clock, seq_id, val, phase_kind, ph_req, ph_rqr, ph_has_val,
    ph_val, acks_mask, then per server s: [present, clock, sid, val]]``
    where ``ph_has_val``/``ph_val`` hold Phase1's pending write or Phase2's
    pending read (disambiguated by ``phase_kind``), and the per-server
    slots hold Phase1's query responses. Client rows use the shared
    register layout.

    Messages (``W = 5``): register kinds 1-4, then Query=5 ``[k, req]``,
    AckQuery=6 / Record=7 ``[k, req, clock, sid, val]``, AckRecord=8
    ``[k, req]``.
    """

    K_QUERY = pr.KIND_INTERNAL_BASE
    K_ACK_QUERY = pr.KIND_INTERNAL_BASE + 1
    K_RECORD = pr.KIND_INTERNAL_BASE + 2
    K_ACK_RECORD = pr.KIND_INTERNAL_BASE + 3

    msg_width = 5

    def __init__(self, client_count: int, server_count: int):
        self.state_width = 9 + 4 * server_count
        self.send_capacity = server_count
        self._init_register_protocol(client_count, server_count, DEFAULT_VALUE)

    # -- host <-> packed ---------------------------------------------------

    def pack_actor_state(self, i, s) -> np.ndarray:
        if i >= self.server_count:
            return pr.pack_client_state(s, self.state_width)
        row = np.zeros((self.state_width,), np.uint32)
        row[0], row[1], row[2] = s.seq[0], int(s.seq[1]), ord(s.val)
        if isinstance(s.phase, Phase1):
            row[3] = 1
            row[4], row[5] = s.phase.request_id, int(s.phase.requester_id)
            if s.phase.write is not None:
                row[6], row[7] = 1, ord(s.phase.write)
            for sid, (seq, val) in s.phase.responses:
                b = 9 + 4 * int(sid)
                row[b : b + 4] = [1, seq[0], int(seq[1]), ord(val)]
        elif isinstance(s.phase, Phase2):
            row[3] = 2
            row[4], row[5] = s.phase.request_id, int(s.phase.requester_id)
            if s.phase.read is not None:
                row[6], row[7] = 1, ord(s.phase.read)
            for a in s.phase.acks:
                row[8] |= np.uint32(1) << np.uint32(int(a))
        return row

    def unpack_actor_state(self, i, row):
        if i >= self.server_count:
            return pr.unpack_client_state(row)
        row = np.asarray(row)
        phase = None
        if int(row[3]) == 1:
            responses = []
            for s in range(self.server_count):
                b = 9 + 4 * s
                if row[b]:
                    responses.append(
                        (
                            Id(s),
                            ((int(row[b + 1]), Id(int(row[b + 2]))), chr(row[b + 3])),
                        )
                    )
            phase = Phase1(
                request_id=int(row[4]),
                requester_id=Id(int(row[5])),
                write=chr(row[7]) if row[6] else None,
                responses=tuple(responses),
            )
        elif int(row[3]) == 2:
            phase = Phase2(
                request_id=int(row[4]),
                requester_id=Id(int(row[5])),
                read=chr(row[7]) if row[6] else None,
                acks=tuple(
                    Id(b)
                    for b in range(self.server_count)
                    if int(row[8]) & (1 << b)
                ),
            )
        return AbdState(
            seq=(int(row[0]), Id(int(row[1]))), val=chr(row[2]), phase=phase
        )

    def pack_msg(self, msg) -> np.ndarray:
        vec = np.zeros((self.msg_width,), np.uint32)
        if isinstance(msg, Put):
            vec[:3] = [pr.K_PUT, msg.request_id, ord(msg.value)]
        elif isinstance(msg, Get):
            vec[:2] = [pr.K_GET, msg.request_id]
        elif isinstance(msg, PutOk):
            vec[:2] = [pr.K_PUT_OK, msg.request_id]
        elif isinstance(msg, GetOk):
            vec[:3] = [pr.K_GET_OK, msg.request_id, ord(msg.value)]
        elif isinstance(msg, Internal):
            inner = msg.msg
            kind = inner[0]
            if kind == "Query":
                vec[:2] = [self.K_QUERY, inner[1]]
            elif kind == "AckQuery":
                vec[:5] = [
                    self.K_ACK_QUERY,
                    inner[1],
                    inner[2][0],
                    int(inner[2][1]),
                    ord(inner[3]),
                ]
            elif kind == "Record":
                vec[:5] = [
                    self.K_RECORD,
                    inner[1],
                    inner[2][0],
                    int(inner[2][1]),
                    ord(inner[3]),
                ]
            elif kind == "AckRecord":
                vec[:2] = [self.K_ACK_RECORD, inner[1]]
            else:
                raise ValueError(f"unknown internal message: {inner!r}")
        else:
            raise TypeError(f"cannot pack message: {msg!r}")
        return vec

    def unpack_msg(self, vec):
        vec = np.asarray(vec)
        k = int(vec[0])
        if k == pr.K_PUT:
            return Put(int(vec[1]), chr(vec[2]))
        if k == pr.K_GET:
            return Get(int(vec[1]))
        if k == pr.K_PUT_OK:
            return PutOk(int(vec[1]))
        if k == pr.K_GET_OK:
            return GetOk(int(vec[1]), chr(vec[2]))
        if k == self.K_QUERY:
            return Internal(("Query", int(vec[1])))
        seq = (int(vec[2]), Id(int(vec[3])))
        if k == self.K_ACK_QUERY:
            return Internal(("AckQuery", int(vec[1]), seq, chr(vec[4])))
        if k == self.K_RECORD:
            return Internal(("Record", int(vec[1]), seq, chr(vec[4])))
        if k == self.K_ACK_RECORD:
            return Internal(("AckRecord", int(vec[1])))
        raise ValueError(f"unknown packed message kind: {k}")

    # -- traceable kernels -------------------------------------------------

    def on_msg_branches(self, model):
        import jax
        import jax.numpy as jnp

        u = jnp.uint32
        Ns = self.server_count
        maj = majority(Ns)
        no_sends, send_row, broadcast = pr.trace_helpers(self, Ns)

        def seq_gt(c1, s1, c2, s2):
            return (c1 > c2) | ((c1 == c2) & (s1 > s2))

        def server_on_msg(me, row, src, msg):
            kind = msg[0]
            meu = me.astype(u)
            srcu = src.astype(u)
            z = u(0)
            ns = no_sends()
            sq_c, sq_s, val = row[0], row[1], row[2]
            phase = row[3]
            ph_req, ph_rqr = row[4], row[5]
            ph_has, ph_val = row[6], row[7]
            acks = row[8]
            req = msg[1]

            # ---- Put/Get (idle): start phase 1 ----------------------------
            start_fire = (
                ((kind == u(pr.K_PUT)) | (kind == u(pr.K_GET)))
                & (phase == 0)
            )
            is_put = kind == u(pr.K_PUT)
            start_row = (
                row.at[3].set(u(1)).at[4].set(req).at[5].set(srcu)
                .at[6].set(jnp.where(is_put, u(1), z))
                .at[7].set(jnp.where(is_put, msg[2], z))
            )
            own_resp = jnp.stack([u(1), sq_c, sq_s, val])
            for s in range(Ns):
                b = 9 + 4 * s
                ent = jnp.where(u(s) == meu, own_resp, jnp.zeros((4,), u))
                start_row = start_row.at[b : b + 4].set(ent)
            start_sends = broadcast(meu, u(self.K_QUERY), req)

            # ---- Query: answer with current (seq, val) --------------------
            query_fire = kind == u(self.K_QUERY)
            query_sends = ns.at[0].set(
                send_row(srcu, u(self.K_ACK_QUERY), req, sq_c, sq_s, val)
            )

            # ---- AckQuery (phase 1, matching request) ---------------------
            ackq_fire = (
                (kind == u(self.K_ACK_QUERY)) & (phase == 1) & (ph_req == req)
            )
            resp_ent = jnp.stack([u(1), msg[2], msg[3], msg[4]])
            aq_row = row
            for s in range(Ns):
                b = 9 + 4 * s
                aq_row = aq_row.at[b : b + 4].set(
                    jnp.where(srcu == u(s), resp_ent, aq_row[b : b + 4])
                )
            count = z
            for s in range(Ns):
                count = count + aq_row[9 + 4 * s]
            quorum = count == u(maj)
            # max response by seq (sequencers are distinct).
            best = aq_row[9:13]
            for s in range(1, Ns):
                ent = aq_row[9 + 4 * s : 13 + 4 * s]
                better = (ent[0] > best[0]) | (
                    (ent[0] == best[0]) & seq_gt(ent[1], ent[2], best[1], best[2])
                )
                best = jnp.where(better, ent, best)
            m_c, m_s, m_v = best[1], best[2], best[3]
            w_c, w_s, w_v = m_c + 1, meu, ph_val  # write: bump clock
            n_c = jnp.where(ph_has == 1, w_c, m_c)
            n_s = jnp.where(ph_has == 1, w_s, m_s)
            n_v = jnp.where(ph_has == 1, w_v, m_v)
            adopt = seq_gt(n_c, n_s, sq_c, sq_s)
            q_row = (
                aq_row.at[0].set(jnp.where(adopt, n_c, sq_c))
                .at[1].set(jnp.where(adopt, n_s, sq_s))
                .at[2].set(jnp.where(adopt, n_v, val))
                .at[3].set(u(2))
                .at[6].set(jnp.where(ph_has == 1, z, u(1)))
                .at[7].set(jnp.where(ph_has == 1, z, m_v))
                .at[8].set(u(1) << meu)
            )
            for s in range(Ns):
                b = 9 + 4 * s
                q_row = q_row.at[b : b + 4].set(jnp.zeros((4,), u))
            q_sends = broadcast(meu, u(self.K_RECORD), ph_req, n_c, n_s, n_v)
            aq_row = jnp.where(quorum, q_row, aq_row)
            aq_sends = jnp.where(quorum, q_sends, ns)

            # ---- Record: ack; adopt if newer ------------------------------
            rec_fire = kind == u(self.K_RECORD)
            rec_adopt = seq_gt(msg[2], msg[3], sq_c, sq_s)
            rec_row = (
                row.at[0].set(jnp.where(rec_adopt, msg[2], sq_c))
                .at[1].set(jnp.where(rec_adopt, msg[3], sq_s))
                .at[2].set(jnp.where(rec_adopt, msg[4], val))
            )
            rec_sends = ns.at[0].set(
                send_row(srcu, u(self.K_ACK_RECORD), req)
            )

            # ---- AckRecord (phase 2, matching, new acker) -----------------
            ackr_fire = (
                (kind == u(self.K_ACK_RECORD))
                & (phase == 2)
                & (ph_req == req)
                & (((acks >> srcu) & u(1)) == 0)
            )
            acks2 = acks | (u(1) << srcu)
            r_quorum = jax.lax.population_count(acks2) == u(maj)
            done_row = (
                row.at[3].set(z).at[4].set(z).at[5].set(z)
                .at[6].set(z).at[7].set(z).at[8].set(z)
            )
            cont_row = row.at[8].set(acks2)
            ar_row = jnp.where(r_quorum, done_row, cont_row)
            reply = jnp.where(
                ph_has == 1,
                send_row(ph_rqr, u(pr.K_GET_OK), ph_req, ph_val),
                send_row(ph_rqr, u(pr.K_PUT_OK), ph_req),
            )
            ar_sends = jnp.where(r_quorum, ns.at[0].set(reply), ns)

            # ---- select ----------------------------------------------------
            row_out = row
            sends = ns
            changed = jnp.bool_(False)
            for fire, r, sd, ch in (
                (start_fire, start_row, start_sends, jnp.bool_(True)),
                (query_fire, row, query_sends, jnp.bool_(False)),
                (ackq_fire, aq_row, aq_sends, jnp.bool_(True)),
                (rec_fire, rec_row, rec_sends, rec_adopt),
                (ackr_fire, ar_row, ar_sends, jnp.bool_(True)),
            ):
                row_out = jnp.where(fire, r, row_out)
                sends = jnp.where(fire, sd, sends)
                changed = jnp.where(fire, ch, changed)
            return row_out, sends, z, z, changed

        client = pr.client_on_msg_branch(self, self.put_count, Ns)
        return [server_on_msg, client]


@dataclass
class AbdModelCfg:
    client_count: int
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )
    envelope_capacity: int = 8
    # Ordered networks only: per-flow FIFO depth. None picks 2 for
    # 2-server configs — measured-exact there (quorum == all servers, so
    # every reply drains before the client's next phase; the full 2c/2s
    # and 3c/2s spaces never exceed depth 2, and the count oracles pin
    # it) — and the legacy 8 otherwise: with 3+ servers the quorum can
    # complete ops without a laggard replica, whose server->server
    # replication FIFO then accumulates ~2 messages per coordinated op
    # (4c/3s reaches depth 5 within 22K states), so NO small bound is
    # protocol-safe. Either way the capacity is a modeling boundary:
    # device-side overflow prunes the transition silently, and only a
    # host-parity / pinned-count check certifies a given value exact.
    flow_capacity: int | None = None

    def into_model(self) -> ActorModel:
        model = PackedActorModel(
            codec=AbdPackedCodec(self.client_count, self.server_count),
            cfg=self,
            init_history=LinearizabilityTester(Register(DEFAULT_VALUE)),
        ).with_envelope_capacity(self.envelope_capacity)
        if self.network.kind == "ordered":
            # Clients never message clients and nobody messages itself:
            # the flow table drops to the structurally reachable pairs
            # (~4x fewer packed words + a ~2x smaller action grid on
            # 3c/2s — the state's words were ~87% flow padding).
            if self.flow_capacity is not None:
                depth = self.flow_capacity
            else:
                depth = 2 if self.server_count == 2 else 8
            model = model.with_flow_pairs(
                pr.register_flow_pairs(self.client_count, self.server_count)
            ).with_flow_capacity(depth)
        for i in range(self.server_count):
            model.actor(AbdActor(model_peers(i, self.server_count)))
        for _ in range(self.client_count):
            model.actor(
                RegisterClient(put_count=1, server_count=self.server_count)
            )

        def value_chosen(_model, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                    return True
            return False

        return (
            model.init_network(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
