"""Two-phase commit (subset of the Gray/Lamport "Consensus on Transaction
Commit" TLA+ spec).

State: per-RM states + transaction-manager state + prepared flags + a message
set. Exact oracle counts: 3 RMs = 288 states, 5 RMs = 8,832, 5 RMs with
symmetry = 665.

Reference: ``/root/reference/examples/2pc.rs``. The packed TPU counterpart is
``stateright_tpu.models.packed_two_phase_commit`` (state fits in a few u32s:
``Message::Prepared{rm}`` bounds the message set to N+2 distinct values).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, List, Tuple

from ..core.model import Model, Property
from ..utils.rewrite import RewritePlan

# RM states
WORKING, PREPARED, COMMITTED, ABORTED = "Working", "Prepared", "Committed", "Aborted"
# TM states
TM_INIT, TM_COMMITTED, TM_ABORTED = "Init", "Committed", "Aborted"
# Messages: ("Prepared", rm) | ("Commit",) | ("Abort",)
COMMIT_MSG = ("Commit",)
ABORT_MSG = ("Abort",)


def prepared_msg(rm: int) -> Tuple:
    return ("Prepared", rm)


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: Tuple[str, ...]
    tm_state: str
    tm_prepared: Tuple[bool, ...]
    msgs: FrozenSet[Tuple]

    def representative(self) -> "TwoPhaseState":
        plan = RewritePlan.from_values_to_sort(self.rm_state)
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("Prepared", plan.mapping[m[1]]) if m[0] == "Prepared" else m
                for m in self.msgs
            ),
        )


class TwoPhaseSys(Model):
    def __init__(self, rm_count: int):
        self.rm_count = rm_count

    def init_states(self) -> List[TwoPhaseState]:
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * self.rm_count,
                tm_state=TM_INIT,
                tm_prepared=(False,) * self.rm_count,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState, actions: List) -> None:
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            actions.append(("TmCommit",))
        if state.tm_state == TM_INIT:
            actions.append(("TmAbort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and prepared_msg(rm) in state.msgs:
                actions.append(("TmRcvPrepared", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmPrepare", rm))
                actions.append(("RmChooseToAbort", rm))
            if COMMIT_MSG in state.msgs:
                actions.append(("RmRcvCommitMsg", rm))
            if ABORT_MSG in state.msgs:
                actions.append(("RmRcvAbortMsg", rm))

    def next_state(self, state: TwoPhaseState, action) -> TwoPhaseState:
        kind = action[0]
        rm_state = list(state.rm_state)
        tm_prepared = list(state.tm_prepared)
        tm_state = state.tm_state
        msgs = state.msgs
        if kind == "TmRcvPrepared":
            tm_prepared[action[1]] = True
        elif kind == "TmCommit":
            tm_state = TM_COMMITTED
            msgs = msgs | {COMMIT_MSG}
        elif kind == "TmAbort":
            tm_state = TM_ABORTED
            msgs = msgs | {ABORT_MSG}
        elif kind == "RmPrepare":
            rm_state[action[1]] = PREPARED
            msgs = msgs | {prepared_msg(action[1])}
        elif kind == "RmChooseToAbort":
            rm_state[action[1]] = ABORTED
        elif kind == "RmRcvCommitMsg":
            rm_state[action[1]] = COMMITTED
        elif kind == "RmRcvAbortMsg":
            rm_state[action[1]] = ABORTED
        else:
            raise ValueError(f"unknown action {action!r}")
        return TwoPhaseState(
            rm_state=tuple(rm_state),
            tm_state=tm_state,
            tm_prepared=tuple(tm_prepared),
            msgs=msgs,
        )

    def properties(self) -> List[Property]:
        return [
            Property.sometimes(
                "abort agreement",
                lambda _, state: all(s == ABORTED for s in state.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda _, state: all(s == COMMITTED for s in state.rm_state),
            ),
            Property.always(
                "consistent",
                lambda _, state: not (
                    ABORTED in state.rm_state and COMMITTED in state.rm_state
                ),
            ),
        ]
