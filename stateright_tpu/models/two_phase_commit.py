"""Two-phase commit (subset of the Gray/Lamport "Consensus on Transaction
Commit" TLA+ spec).

State: per-RM states + transaction-manager state + prepared flags + a message
set. Exact oracle counts: 3 RMs = 288 states, 5 RMs = 8,832, 5 RMs with
symmetry = 665.

Reference: ``/root/reference/examples/2pc.rs``. ``TwoPhaseSys`` also
implements the ``BatchableModel`` packed protocol — the state fits in a few
u32 words (``Message::Prepared{rm}`` bounds the message set to N+2 distinct
values, so it packs into one bitmask), making this the minimum end-to-end
TPU slice per SURVEY §7.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, List, Tuple

import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Model, Property
from ..utils.rewrite import RewritePlan

# RM states
WORKING, PREPARED, COMMITTED, ABORTED = "Working", "Prepared", "Committed", "Aborted"
# TM states
TM_INIT, TM_COMMITTED, TM_ABORTED = "Init", "Committed", "Aborted"
# Messages: ("Prepared", rm) | ("Commit",) | ("Abort",)
COMMIT_MSG = ("Commit",)
ABORT_MSG = ("Abort",)


def prepared_msg(rm: int) -> Tuple:
    return ("Prepared", rm)


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: Tuple[str, ...]
    tm_state: str
    tm_prepared: Tuple[bool, ...]
    msgs: FrozenSet[Tuple]

    def _permuted(self, plan: RewritePlan) -> "TwoPhaseState":
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("Prepared", plan.mapping[m[1]]) if m[0] == "Prepared" else m
                for m in self.msgs
            ),
        )

    def representative(self) -> "TwoPhaseState":
        # Reference-parity sort heuristic (``examples/2pc.rs:203-228``):
        # NOT a canonical form — see orbit_representative.
        return self._permuted(RewritePlan.from_values_to_sort(self.rm_state))

    def orbit_representative(self) -> "TwoPhaseState":
        """True orbit canonical form (see ``utils.rewrite.orbit_min``):
        traversal-order-independent, matching the device checkers'
        minimum-fingerprint symmetry semantics."""
        from ..utils.rewrite import orbit_min

        return orbit_min(len(self.rm_state), self._permuted)


# Packed codes (uint32). Order matters only for the packed representation.
_RM_CODE = {WORKING: 0, PREPARED: 1, COMMITTED: 2, ABORTED: 3}
_RM_NAME = {v: k for k, v in _RM_CODE.items()}
_TM_CODE = {TM_INIT: 0, TM_COMMITTED: 1, TM_ABORTED: 2}
_TM_NAME = {v: k for k, v in _TM_CODE.items()}


class TwoPhaseSys(Model, BatchableModel):
    def __init__(self, rm_count: int):
        self.rm_count = rm_count

    def init_states(self) -> List[TwoPhaseState]:
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * self.rm_count,
                tm_state=TM_INIT,
                tm_prepared=(False,) * self.rm_count,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState, actions: List) -> None:
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            actions.append(("TmCommit",))
        if state.tm_state == TM_INIT:
            actions.append(("TmAbort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and prepared_msg(rm) in state.msgs:
                actions.append(("TmRcvPrepared", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmPrepare", rm))
                actions.append(("RmChooseToAbort", rm))
            if COMMIT_MSG in state.msgs:
                actions.append(("RmRcvCommitMsg", rm))
            if ABORT_MSG in state.msgs:
                actions.append(("RmRcvAbortMsg", rm))

    def next_state(self, state: TwoPhaseState, action) -> TwoPhaseState:
        kind = action[0]
        rm_state = list(state.rm_state)
        tm_prepared = list(state.tm_prepared)
        tm_state = state.tm_state
        msgs = state.msgs
        if kind == "TmRcvPrepared":
            tm_prepared[action[1]] = True
        elif kind == "TmCommit":
            tm_state = TM_COMMITTED
            msgs = msgs | {COMMIT_MSG}
        elif kind == "TmAbort":
            tm_state = TM_ABORTED
            msgs = msgs | {ABORT_MSG}
        elif kind == "RmPrepare":
            rm_state[action[1]] = PREPARED
            msgs = msgs | {prepared_msg(action[1])}
        elif kind == "RmChooseToAbort":
            rm_state[action[1]] = ABORTED
        elif kind == "RmRcvCommitMsg":
            rm_state[action[1]] = COMMITTED
        elif kind == "RmRcvAbortMsg":
            rm_state[action[1]] = ABORTED
        else:
            raise ValueError(f"unknown action {action!r}")
        return TwoPhaseState(
            rm_state=tuple(rm_state),
            tm_state=tm_state,
            tm_prepared=tuple(tm_prepared),
            msgs=msgs,
        )

    def properties(self) -> List[Property]:
        return [
            Property.sometimes(
                "abort agreement",
                lambda _, state: all(s == ABORTED for s in state.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda _, state: all(s == COMMITTED for s in state.rm_state),
            ),
            Property.always(
                "consistent",
                lambda _, state: not (
                    ABORTED in state.rm_state and COMMITTED in state.rm_state
                ),
            ),
        ]

    # -- BatchableModel (packed protocol) ----------------------------------
    #
    # Packed state layout (all uint32):
    #   rm:       (N,) per-RM code (0=Working 1=Prepared 2=Committed 3=Aborted)
    #   tm:       ()   TM code     (0=Init 1=Committed 2=Aborted)
    #   prepared: ()   bitmask of tm_prepared flags
    #   msgs:     ()   bitmask: bit rm = Prepared{rm}, bit N = Commit,
    #                  bit N+1 = Abort
    #
    # Dense action ids (A = 2 + 5N):
    #   0 = TmCommit, 1 = TmAbort,
    #   2 + rm*5 + k with k: 0=TmRcvPrepared 1=RmPrepare 2=RmChooseToAbort
    #                        3=RmRcvCommitMsg 4=RmRcvAbortMsg

    def packed_action_count(self) -> int:
        return 2 + 5 * self.rm_count

    def packed_action_labels(self):
        # Dense-id labels mirroring packed_step's dispatch (aid 0/1 are
        # the TM actions, then 5 per RM) — the coverage ledger's
        # per-action axis reads like the host actions() names.
        labels = ["TmCommit", "TmAbort"]
        for rm in range(self.rm_count):
            labels += [
                f"TmRcvPrepared_{rm}",
                f"RmPrepare_{rm}",
                f"RmChooseToAbort_{rm}",
                f"RmRcvCommitMsg_{rm}",
                f"RmRcvAbortMsg_{rm}",
            ]
        return labels

    def packed_init_states(self):
        import jax.numpy as jnp

        n = self.rm_count
        return {
            "rm": jnp.zeros((1, n), jnp.uint32),
            "tm": jnp.zeros((1,), jnp.uint32),
            "prepared": jnp.zeros((1,), jnp.uint32),
            "msgs": jnp.zeros((1,), jnp.uint32),
        }

    def packed_step(self, state, action_id):
        import jax.numpy as jnp

        n = self.rm_count
        aid = action_id.astype(jnp.int32)
        rm = jnp.clip((aid - 2) // 5, 0, n - 1)
        k = (aid - 2) % 5
        is_rm = aid >= 2
        rmu = rm.astype(jnp.uint32)
        bit = jnp.uint32(1) << rmu
        rms, tm = state["rm"], state["tm"]
        prepared, msgs = state["prepared"], state["msgs"]

        tm_init = tm == 0
        all_prepared = prepared == jnp.uint32((1 << n) - 1)
        commit_in = ((msgs >> jnp.uint32(n)) & 1) == 1
        abort_in = ((msgs >> jnp.uint32(n + 1)) & 1) == 1
        prep_msg_in = ((msgs >> rmu) & 1) == 1
        rm_working = rms[rm] == 0

        valid = jnp.select(
            [aid == 0, aid == 1, k == 0, k == 1, k == 2, k == 3],
            [
                tm_init & all_prepared,
                tm_init,
                tm_init & prep_msg_in,
                rm_working,
                rm_working,
                commit_in,
            ],
            abort_in,  # k == 4
        )

        u0 = jnp.uint32(0)
        new_tm = jnp.where(
            aid == 0, jnp.uint32(1), jnp.where(aid == 1, jnp.uint32(2), tm)
        )
        new_msgs = (
            msgs
            | jnp.where(aid == 0, jnp.uint32(1 << n), u0)
            | jnp.where(aid == 1, jnp.uint32(1 << (n + 1)), u0)
            | jnp.where(is_rm & (k == 1), bit, u0)
        )
        new_prepared = prepared | jnp.where(is_rm & (k == 0), bit, u0)
        # k: 1=Prepare→1, 2=ChooseToAbort→3, 3=RcvCommit→2, 4=RcvAbort→3
        rm_val = jnp.select(
            [k == 1, k == 2, k == 3], [jnp.uint32(1), jnp.uint32(3), jnp.uint32(2)],
            jnp.uint32(3),
        )
        writes_rm = is_rm & (k != 0)
        new_rms = jnp.where(
            (jnp.arange(n) == rm) & writes_rm, rm_val, rms
        ).astype(jnp.uint32)
        next_state = {
            "rm": new_rms,
            "tm": new_tm,
            "prepared": new_prepared,
            "msgs": new_msgs,
        }
        return next_state, valid

    def packed_conditions(self):
        import jax.numpy as jnp

        return [
            lambda st: jnp.all(st["rm"] == 3),  # abort agreement
            lambda st: jnp.all(st["rm"] == 2),  # commit agreement
            lambda st: ~(jnp.any(st["rm"] == 3) & jnp.any(st["rm"] == 2)),
        ]

    # -- symmetry (orbit-proper; see core/batch.py) ------------------------

    def packed_symmetry(self):
        from ..core.batch import permutation_tables

        return permutation_tables(self.rm_count)

    def packed_apply_permutation(self, state, new_to_old, old_to_new):
        """RM-permutation group action: permute per-RM codes and the
        RM-indexed bits of the prepared/message bitmasks (device analog of
        the host ``TwoPhaseState`` rewrite)."""
        import jax.numpy as jnp

        n = self.rm_count
        n2o = new_to_old.astype(jnp.uint32)

        def permute_bits(mask):
            bits = (mask >> n2o) & jnp.uint32(1)
            return (bits << jnp.arange(n, dtype=jnp.uint32)).sum(
                dtype=jnp.uint32
            )

        low_mask = jnp.uint32((1 << n) - 1)
        return {
            "rm": state["rm"][new_to_old],
            "tm": state["tm"],
            "prepared": permute_bits(state["prepared"]),
            "msgs": permute_bits(state["msgs"] & low_mask)
            | (state["msgs"] & ~low_mask),
        }

    def packed_refine_colors(self, state, colors):
        """Equivariant WL round (see ``core/batch.py``): each RM's view is
        fully local — its code plus its ``prepared`` and ``Prepared{rm}``
        bits — so one round separates every non-automorphic pair and color
        ties are always genuine automorphisms (swapping two RMs with equal
        triples fixes the state exactly). The global TM fields are
        permutation-invariant and add nothing."""
        import jax.numpy as jnp

        from ..ops.fingerprint import avalanche32

        u = jnp.uint32
        idx = jnp.arange(self.rm_count, dtype=u)
        prep = (state["prepared"] >> idx) & u(1)
        msg = (state["msgs"] >> idx) & u(1)
        return avalanche32(
            colors * u(0x9E3779B1)
            ^ state["rm"] * u(0x01000193)
            ^ prep * u(0xCC9E2D51)
            ^ msg * u(0x1B873593)
        )

    def pack_state(self, host_state: TwoPhaseState):
        n = self.rm_count
        msgs = 0
        for m in host_state.msgs:
            if m[0] == "Prepared":
                msgs |= 1 << m[1]
            elif m == COMMIT_MSG:
                msgs |= 1 << n
            elif m == ABORT_MSG:
                msgs |= 1 << (n + 1)
        prepared = 0
        for i, flag in enumerate(host_state.tm_prepared):
            if flag:
                prepared |= 1 << i
        return {
            "rm": np.array(
                [_RM_CODE[s] for s in host_state.rm_state], np.uint32
            ),
            "tm": np.uint32(_TM_CODE[host_state.tm_state]),
            "prepared": np.uint32(prepared),
            "msgs": np.uint32(msgs),
        }

    def unpack_state(self, packed) -> TwoPhaseState:
        n = self.rm_count
        msgs_mask = int(packed["msgs"])
        msgs = set()
        for rm in range(n):
            if msgs_mask & (1 << rm):
                msgs.add(prepared_msg(rm))
        if msgs_mask & (1 << n):
            msgs.add(COMMIT_MSG)
        if msgs_mask & (1 << (n + 1)):
            msgs.add(ABORT_MSG)
        prepared = int(packed["prepared"])
        return TwoPhaseState(
            rm_state=tuple(_RM_NAME[int(c)] for c in np.asarray(packed["rm"])),
            tm_state=_TM_NAME[int(packed["tm"])],
            tm_prepared=tuple(bool(prepared & (1 << i)) for i in range(n)),
            msgs=frozenset(msgs),
        )
