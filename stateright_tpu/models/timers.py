"""Pinger actors driven purely by named timers — exercises the Timer plumbing
(set/cancel/renew, no-op-with-timer pruning).

Reference: ``/root/reference/examples/timers.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from ..actor import Actor, ActorModel, Id, Network, Out, model_peers, model_timeout
from ..core.model import Expectation

PING, PONG = "Ping", "Pong"
EVEN, ODD, NO_OP = "Even", "Odd", "NoOp"


@dataclass(frozen=True)
class PingerState:
    sent: int
    received: int


class PingerActor(Actor):
    def __init__(self, peer_ids: List[Id]):
        self.peer_ids = peer_ids

    def on_start(self, id: Id, o: Out) -> PingerState:
        o.set_timer(EVEN, model_timeout())
        o.set_timer(ODD, model_timeout())
        o.set_timer(NO_OP, model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: PingerState, src: Id, msg, o: Out):
        if msg == PING:
            o.send(src, PONG)
            return None
        if msg == PONG:
            return PingerState(sent=state.sent, received=state.received + 1)
        return None

    def on_timeout(self, id: Id, state: PingerState, timer, o: Out):
        if timer == EVEN:
            o.set_timer(EVEN, model_timeout())
            sent = state.sent
            for dst in self.peer_ids:
                if int(dst) % 2 == 0:
                    sent += 1
                    o.send(dst, PING)
            return PingerState(sent=sent, received=state.received) if sent != state.sent else None
        if timer == ODD:
            o.set_timer(ODD, model_timeout())
            sent = state.sent
            for dst in self.peer_ids:
                if int(dst) % 2 != 0:
                    sent += 1
                    o.send(dst, PING)
            return PingerState(sent=sent, received=state.received) if sent != state.sent else None
        if timer == NO_OP:
            o.set_timer(NO_OP, model_timeout())
            return None
        return None


@dataclass
class PingerModelCfg:
    server_count: int
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        model = ActorModel(cfg=self, init_history=None)
        for i in range(self.server_count):
            model.actor(PingerActor(model_peers(i, self.server_count)))
        return model.init_network(self.network).property(
            Expectation.ALWAYS, "true", lambda _m, _s: True
        )
