"""Racy shared-counter models.

``Increment``: N threads each read the shared counter into a local, then write
local+1 back — the classic lost-update race; ``always "fin"`` is intentionally
falsifiable. ``IncrementLock``: the same machine guarded by a lock; ``"fin"``
and ``"mutex"`` hold.

Reference: ``/root/reference/examples/increment.rs`` and
``increment_lock.rs``. These are measurement configs in BASELINE.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from ..core.model import Model, Property

# ProcState is (t, pc): thread-local value and program counter.


@dataclass(frozen=True)
class IncrementState:
    i: int
    s: Tuple[Tuple[int, int], ...]  # per-thread (t, pc)

    def representative(self) -> "IncrementState":
        return IncrementState(i=self.i, s=tuple(sorted(self.s)))


class Increment(Model):
    """pc 1: may Read (t <- i, pc 2); pc 2: may Write (i <- t+1, pc 3)."""

    def __init__(self, thread_count: int):
        self.thread_count = thread_count

    def init_states(self):
        return [IncrementState(i=0, s=((0, 1),) * self.thread_count)]

    def actions(self, state, actions):
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))

    def next_state(self, state, action):
        kind, n = action
        s = list(state.s)
        if kind == "Read":
            s[n] = (state.i, 2)
            return IncrementState(i=state.i, s=tuple(s))
        t, _pc = s[n]
        s[n] = (t, 3)
        return IncrementState(i=(t + 1) % 256, s=tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _, state: sum(1 for _t, pc in state.s if pc == 3)
                == state.i,
            )
        ]


@dataclass(frozen=True)
class IncrementLockState:
    i: int
    lock: bool
    s: Tuple[Tuple[int, int], ...]

    def representative(self) -> "IncrementLockState":
        return IncrementLockState(i=self.i, lock=self.lock, s=tuple(sorted(self.s)))


class IncrementLock(Model):
    """Same counter machine with a lock; both properties hold."""

    def __init__(self, thread_count: int):
        self.thread_count = thread_count

    def init_states(self):
        return [
            IncrementLockState(i=0, lock=False, s=((0, 0),) * self.thread_count)
        ]

    def actions(self, state, actions):
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 0 and not state.lock:
                actions.append(("Lock", thread_id))
            elif pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))
            elif pc == 3 and state.lock:
                actions.append(("Release", thread_id))

    def next_state(self, state, action):
        kind, n = action
        s = list(state.s)
        t, pc = s[n]
        if kind == "Lock":
            s[n] = (t, 1)
            return IncrementLockState(i=state.i, lock=True, s=tuple(s))
        if kind == "Read":
            s[n] = (state.i, 2)
            return IncrementLockState(i=state.i, lock=state.lock, s=tuple(s))
        if kind == "Write":
            s[n] = (t, 3)
            return IncrementLockState(
                i=(t + 1) % 256, lock=state.lock, s=tuple(s)
            )
        s[n] = (t, 4)
        return IncrementLockState(i=state.i, lock=False, s=tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _, state: sum(1 for _t, pc in state.s if pc >= 3)
                == state.i,
            ),
            Property.always(
                "mutex",
                lambda _, state: sum(1 for _t, pc in state.s if 1 <= pc < 4)
                <= 1,
            ),
        ]
