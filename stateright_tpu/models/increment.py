"""Racy shared-counter models.

``Increment``: N threads each read the shared counter into a local, then write
local+1 back — the classic lost-update race; ``always "fin"`` is intentionally
falsifiable. ``IncrementLock``: the same machine guarded by a lock; ``"fin"``
and ``"mutex"`` hold.

Reference: ``/root/reference/examples/increment.rs`` and
``increment_lock.rs``. These are measurement configs in BASELINE.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core.batch import BatchableModel
from ..core.model import Model, Property

# ProcState is (t, pc): thread-local value and program counter.


@dataclass(frozen=True)
class IncrementState:
    i: int
    s: Tuple[Tuple[int, int], ...]  # per-thread (t, pc)

    def representative(self) -> "IncrementState":
        return IncrementState(i=self.i, s=tuple(sorted(self.s)))


class Increment(Model, BatchableModel):
    """pc 1: may Read (t <- i, pc 2); pc 2: may Write (i <- t+1, pc 3).

    Packed layout (device path): ``i`` scalar u32, ``t``/``pc`` per-thread
    (N,) u32 vectors. One dense action per thread (the pc uniquely selects
    the enabled op, so the successor set matches the host model's separate
    Read/Write actions exactly).
    """

    def __init__(self, thread_count: int):
        self.thread_count = thread_count

    def init_states(self):
        return [IncrementState(i=0, s=((0, 1),) * self.thread_count)]

    def actions(self, state, actions):
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))

    def next_state(self, state, action):
        kind, n = action
        s = list(state.s)
        if kind == "Read":
            s[n] = (state.i, 2)
            return IncrementState(i=state.i, s=tuple(s))
        t, _pc = s[n]
        s[n] = (t, 3)
        return IncrementState(i=(t + 1) % 256, s=tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _, state: sum(1 for _t, pc in state.s if pc == 3)
                == state.i,
            )
        ]

    # -- BatchableModel (packed protocol) ----------------------------------

    def packed_action_count(self) -> int:
        return self.thread_count

    def packed_init_states(self):
        import jax.numpy as jnp

        n = self.thread_count
        return {
            "i": jnp.zeros((1,), jnp.uint32),
            "t": jnp.zeros((1, n), jnp.uint32),
            "pc": jnp.ones((1, n), jnp.uint32),
        }

    def packed_step(self, state, action_id):
        import jax.numpy as jnp

        tid = action_id.astype(jnp.int32)
        i, t, pc = state["i"], state["t"], state["pc"]
        pc_n = pc[tid]
        is_read = pc_n == 1
        valid = is_read | (pc_n == 2)
        new = {
            "i": jnp.where(
                is_read, i, (t[tid] + jnp.uint32(1)) & jnp.uint32(0xFF)
            ),
            "t": t.at[tid].set(jnp.where(is_read, i, t[tid])),
            "pc": pc.at[tid].set(pc_n + jnp.uint32(1)),
        }
        return new, valid

    def packed_conditions(self):
        import jax.numpy as jnp

        return [
            lambda st: (st["pc"] == 3).sum(dtype=jnp.uint32) == st["i"],
        ]

    def packed_symmetry(self):
        from ..core.batch import permutation_tables

        return permutation_tables(self.thread_count)

    def packed_apply_permutation(self, state, new_to_old, old_to_new):
        return {
            "i": state["i"],
            "t": state["t"][new_to_old],
            "pc": state["pc"][new_to_old],
        }

    def packed_refine_colors(self, state, colors):
        """Per-thread data is fully local (no cross-thread references), so
        one equivariant round separates all non-automorphic threads."""
        import jax.numpy as jnp

        from ..ops.fingerprint import avalanche32

        u = jnp.uint32
        return avalanche32(
            colors * u(0x9E3779B1)
            ^ state["t"] * u(0x01000193)
            ^ state["pc"] * u(0xCC9E2D51)
        )

    def pack_state(self, host_state: IncrementState):
        return {
            "i": np.uint32(host_state.i),
            "t": np.array([t for t, _pc in host_state.s], np.uint32),
            "pc": np.array([pc for _t, pc in host_state.s], np.uint32),
        }

    def unpack_state(self, packed) -> IncrementState:
        return IncrementState(
            i=int(packed["i"]),
            s=tuple(
                (int(t), int(pc))
                for t, pc in zip(packed["t"], packed["pc"])
            ),
        )


@dataclass(frozen=True)
class IncrementLockState:
    i: int
    lock: bool
    s: Tuple[Tuple[int, int], ...]

    def representative(self) -> "IncrementLockState":
        return IncrementLockState(i=self.i, lock=self.lock, s=tuple(sorted(self.s)))


class IncrementLock(Model, BatchableModel):
    """Same counter machine with a lock; both properties hold.

    Packed layout: ``i``/``lock`` scalar u32, ``t``/``pc`` (N,) u32; one
    dense action per thread (pc + lock uniquely select the enabled op).
    """

    def __init__(self, thread_count: int):
        self.thread_count = thread_count

    def init_states(self):
        return [
            IncrementLockState(i=0, lock=False, s=((0, 0),) * self.thread_count)
        ]

    def actions(self, state, actions):
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 0 and not state.lock:
                actions.append(("Lock", thread_id))
            elif pc == 1:
                actions.append(("Read", thread_id))
            elif pc == 2:
                actions.append(("Write", thread_id))
            elif pc == 3 and state.lock:
                actions.append(("Release", thread_id))

    def next_state(self, state, action):
        kind, n = action
        s = list(state.s)
        t, pc = s[n]
        if kind == "Lock":
            s[n] = (t, 1)
            return IncrementLockState(i=state.i, lock=True, s=tuple(s))
        if kind == "Read":
            s[n] = (state.i, 2)
            return IncrementLockState(i=state.i, lock=state.lock, s=tuple(s))
        if kind == "Write":
            s[n] = (t, 3)
            return IncrementLockState(
                i=(t + 1) % 256, lock=state.lock, s=tuple(s)
            )
        s[n] = (t, 4)
        return IncrementLockState(i=state.i, lock=False, s=tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _, state: sum(1 for _t, pc in state.s if pc >= 3)
                == state.i,
            ),
            Property.always(
                "mutex",
                lambda _, state: sum(1 for _t, pc in state.s if 1 <= pc < 4)
                <= 1,
            ),
        ]

    # -- BatchableModel (packed protocol) ----------------------------------

    def packed_action_count(self) -> int:
        return self.thread_count

    def packed_init_states(self):
        import jax.numpy as jnp

        n = self.thread_count
        return {
            "i": jnp.zeros((1,), jnp.uint32),
            "lock": jnp.zeros((1,), jnp.uint32),
            "t": jnp.zeros((1, n), jnp.uint32),
            "pc": jnp.zeros((1, n), jnp.uint32),
        }

    def packed_step(self, state, action_id):
        import jax.numpy as jnp

        tid = action_id.astype(jnp.int32)
        i, lock = state["i"], state["lock"]
        t, pc = state["t"], state["pc"]
        pc_n = pc[tid]
        unlocked = lock == 0
        is_lock = (pc_n == 0) & unlocked
        is_read = pc_n == 1
        is_write = pc_n == 2
        is_release = (pc_n == 3) & ~unlocked
        valid = is_lock | is_read | is_write | is_release
        new = {
            "i": jnp.where(
                is_write, (t[tid] + jnp.uint32(1)) & jnp.uint32(0xFF), i
            ),
            "lock": jnp.where(
                is_lock, jnp.uint32(1), jnp.where(is_release, jnp.uint32(0), lock)
            ),
            "t": t.at[tid].set(jnp.where(is_read, i, t[tid])),
            "pc": pc.at[tid].set(pc_n + jnp.uint32(1)),
        }
        return new, valid

    def packed_conditions(self):
        import jax.numpy as jnp

        return [
            lambda st: (st["pc"] >= 3).sum(dtype=jnp.uint32) == st["i"],
            lambda st: ((st["pc"] >= 1) & (st["pc"] < 4)).sum(
                dtype=jnp.int32
            )
            <= 1,
        ]

    def packed_symmetry(self):
        from ..core.batch import permutation_tables

        return permutation_tables(self.thread_count)

    def packed_apply_permutation(self, state, new_to_old, old_to_new):
        return {
            "i": state["i"],
            "lock": state["lock"],
            "t": state["t"][new_to_old],
            "pc": state["pc"][new_to_old],
        }

    def packed_refine_colors(self, state, colors):
        """Per-thread data is fully local (the lock holder is implied by
        ``pc``, not an id), so one equivariant round suffices."""
        import jax.numpy as jnp

        from ..ops.fingerprint import avalanche32

        u = jnp.uint32
        return avalanche32(
            colors * u(0x9E3779B1)
            ^ state["t"] * u(0x01000193)
            ^ state["pc"] * u(0xCC9E2D51)
        )

    def pack_state(self, host_state: IncrementLockState):
        return {
            "i": np.uint32(host_state.i),
            "lock": np.uint32(1 if host_state.lock else 0),
            "t": np.array([t for t, _pc in host_state.s], np.uint32),
            "pc": np.array([pc for _t, pc in host_state.s], np.uint32),
        }

    def unpack_state(self, packed) -> IncrementLockState:
        return IncrementLockState(
            i=int(packed["i"]),
            lock=bool(int(packed["lock"])),
            s=tuple(
                (int(t), int(pc))
                for t, pc in zip(packed["t"], packed["pc"])
            ),
        )
