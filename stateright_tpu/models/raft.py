"""Raft leader election, model-checked with lossy networks and symmetry.

A new example required by the BASELINE configs (the reference ships no Raft
example; the actor/builder idioms follow ``/root/reference/examples/paxos.rs``).
Scope is the election subprotocol: election timers fire nondeterministically
(every timing interleaving is explored), candidates solicit votes, a majority
quorum elects a leader which announces itself by heartbeat.

Checked properties:

- ``always "election safety"`` — at most one leader per term (Raft paper §5.2
  invariant); holds under message loss, duplication, and reordering.
- ``sometimes "leader elected"`` — a leader exists (witness the protocol can
  make progress).
- ``eventually "stable leader"`` — *intentionally falsifiable*: repeated
  split votes (or total message loss on lossy networks) can exhaust the term
  boundary with no leader elected, and the checker reports the
  counterexample trace; liveness in Raft requires randomized timeouts, which
  a model checker deliberately explores the adversarial schedules of.

The term bound (``max_term``) is the state-space boundary knob, like the
reference's ``max_nat`` ping-pong bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional

import numpy as np

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    model_peers,
    model_timeout,
)
from ..actor.packed import ActorPackedCodec, PackedActorModel
from ..core.model import Expectation

FOLLOWER, CANDIDATE, LEADER = "Follower", "Candidate", "Leader"
ELECTION = "Election"


def majority(cluster_size: int) -> int:
    return cluster_size // 2 + 1


# Messages (no embedded Ids — src carries the sender, keeping symmetry
# rewriting to the envelope level):
#   ("RequestVote", term)
#   ("Vote", term)            -- a granted vote (denials are silent)
#   ("Heartbeat", term)


@dataclass(frozen=True)
class RaftState:
    role: str
    term: int
    voted_for: Optional[Id]
    votes: FrozenSet[Id]


class RaftActor(Actor):
    def __init__(self, peer_ids: List[Id]):
        self.peer_ids = peer_ids

    def name(self) -> str:
        return "Raft Server"

    def _cluster_size(self) -> int:
        return len(self.peer_ids) + 1

    def on_start(self, id: Id, o: Out) -> RaftState:
        o.set_timer(ELECTION, model_timeout())
        return RaftState(role=FOLLOWER, term=0, voted_for=None, votes=frozenset())

    def on_timeout(self, id: Id, state: RaftState, timer, o: Out):
        if timer != ELECTION:
            return None
        # Start (or restart, on split votes) an election.
        o.set_timer(ELECTION, model_timeout())
        term = state.term + 1
        votes = frozenset([id])
        if len(votes) >= majority(self._cluster_size()):
            # Single-node cluster: the self-vote is already a majority.
            o.cancel_timer(ELECTION)
            return RaftState(role=LEADER, term=term, voted_for=id, votes=votes)
        o.broadcast(self.peer_ids, ("RequestVote", term))
        return RaftState(role=CANDIDATE, term=term, voted_for=id, votes=votes)

    def on_msg(self, id: Id, state: RaftState, src: Id, msg, o: Out):
        kind, term = msg[0], msg[1]
        if kind == "RequestVote":
            if term > state.term:
                # Newer term: adopt it as a follower and grant the vote.
                o.send(src, ("Vote", term))
                return RaftState(
                    role=FOLLOWER, term=term, voted_for=src, votes=frozenset()
                )
            if (
                term == state.term
                and state.role == FOLLOWER
                and state.voted_for in (None, src)
            ):
                o.send(src, ("Vote", term))
                if state.voted_for == src:
                    return None  # duplicate request, vote resent
                return RaftState(
                    role=FOLLOWER,
                    term=term,
                    voted_for=src,
                    votes=state.votes,
                )
            return None  # stale term or vote already cast: deny silently

        if kind == "Vote":
            if state.role != CANDIDATE or term != state.term:
                return None  # stale vote (e.g. from a previous election)
            votes = state.votes | {src}
            if len(votes) >= majority(self._cluster_size()):
                o.cancel_timer(ELECTION)
                o.broadcast(self.peer_ids, ("Heartbeat", state.term))
                return RaftState(
                    role=LEADER,
                    term=state.term,
                    voted_for=state.voted_for,
                    votes=votes,
                )
            if votes == state.votes:
                return None  # duplicate vote
            return RaftState(
                role=CANDIDATE,
                term=state.term,
                voted_for=state.voted_for,
                votes=votes,
            )

        if kind == "Heartbeat":
            if term < state.term:
                return None  # stale leader
            if state.role == FOLLOWER and term == state.term:
                # Already following this term's leader; renewing the election
                # timer alone would be a no-op-with-timer (pruned).
                o.set_timer(ELECTION, model_timeout())
                return None
            o.set_timer(ELECTION, model_timeout())
            return RaftState(
                role=FOLLOWER,
                term=term,
                voted_for=state.voted_for if term == state.term else None,
                votes=frozenset(),
            )

        return None


class RaftPackedCodec(ActorPackedCodec):
    """Packed kernels for ``RaftActor``: the traceable twin of the host
    callbacks above (state row ``[role, term, voted_for+1, votes_bitmask]``,
    message ``[kind, term]`` with kinds RequestVote=1 Vote=2 Heartbeat=3).
    Exact-count parity with the host model is pinned in tests."""

    msg_width = 2
    state_width = 4
    timer_values = [ELECTION]

    K_REQUEST_VOTE, K_VOTE, K_HEARTBEAT = 1, 2, 3
    _KIND_NAME = {1: "RequestVote", 2: "Vote", 3: "Heartbeat"}
    _KIND_CODE = {"RequestVote": 1, "Vote": 2, "Heartbeat": 3}

    def __init__(self, server_count: int):
        self.n = server_count
        self.send_capacity = server_count

    # -- host <-> packed ---------------------------------------------------

    _ROLE_CODE = {FOLLOWER: 0, CANDIDATE: 1, LEADER: 2}
    _ROLE_NAME = {0: FOLLOWER, 1: CANDIDATE, 2: LEADER}

    def pack_actor_state(self, i, s: RaftState) -> np.ndarray:
        votes = 0
        for v in s.votes:
            votes |= 1 << int(v)
        return np.array(
            [
                self._ROLE_CODE[s.role],
                s.term,
                0 if s.voted_for is None else int(s.voted_for) + 1,
                votes,
            ],
            np.uint32,
        )

    def unpack_actor_state(self, i, row) -> RaftState:
        votes = int(row[3])
        return RaftState(
            role=self._ROLE_NAME[int(row[0])],
            term=int(row[1]),
            voted_for=None if int(row[2]) == 0 else Id(int(row[2]) - 1),
            votes=frozenset(
                Id(b) for b in range(self.n) if votes & (1 << b)
            ),
        )

    def pack_msg(self, msg) -> np.ndarray:
        return np.array([self._KIND_CODE[msg[0]], msg[1]], np.uint32)

    def unpack_msg(self, vec):
        return (self._KIND_NAME[int(vec[0])], int(vec[1]))

    # -- traceable kernels -------------------------------------------------

    def _no_sends(self):
        import jax.numpy as jnp

        return jnp.full((self.send_capacity, 1 + self.msg_width), self.SEND_NONE)

    def _broadcast(self, me, kind, term):
        """Sends (kind, term) to every peer of ``me``."""
        import jax.numpy as jnp

        n = self.n
        ids = jnp.arange(n, dtype=jnp.uint32)
        dst = jnp.where(ids == me.astype(jnp.uint32), self.SEND_NONE, ids)
        kinds = jnp.full((n,), kind, jnp.uint32)
        terms = jnp.full((n,), term, jnp.uint32)
        return jnp.stack([dst, kinds, terms], axis=1)

    def on_msg_branches(self, model):
        import jax
        import jax.numpy as jnp

        n = self.n
        maj = majority(n)
        u = jnp.uint32

        def on_msg(me, row, src, msg):
            role, term, voted, votes = row[0], row[1], row[2], row[3]
            kind, mterm = msg[0], msg[1]
            srcu = src.astype(u)
            src_bit = u(1) << srcu
            no_sends = self._no_sends()
            zero = u(0)

            # --- RequestVote ---
            newer = mterm > term
            grant_same = (
                (mterm == term)
                & (role == 0)
                & ((voted == 0) | (voted == srcu + 1))
            )
            rv_grant = newer | grant_same
            rv_changed = newer | (grant_same & (voted != srcu + 1))
            rv_row = jnp.stack(
                [
                    zero,
                    jnp.where(newer, mterm, term),
                    srcu + 1,
                    jnp.where(newer, zero, votes),
                ]
            )
            rv_row = jnp.where(rv_changed, rv_row, row)
            # reply Vote(mterm) to src when granting
            rv_sends = no_sends.at[0].set(
                jnp.where(
                    rv_grant,
                    jnp.stack([srcu, u(self.K_VOTE), mterm]),
                    no_sends[0],
                )
            )

            # --- Vote ---
            votes_new = votes | src_bit
            is_cand = (role == 1) & (mterm == term)
            wins = jax.lax.population_count(votes_new) >= maj
            dup = votes == votes_new
            v_changed = is_cand & ~dup
            v_wins = is_cand & wins
            v_row = jnp.stack(
                [
                    jnp.where(v_wins, u(2), u(1)),
                    term,
                    voted,
                    votes_new,
                ]
            )
            v_row = jnp.where(v_changed | v_wins, v_row, row)
            v_sends = jnp.where(
                v_wins, self._broadcast(me, u(self.K_HEARTBEAT), term), no_sends
            )
            v_cancel = jnp.where(v_wins, u(1), zero)

            # --- Heartbeat ---
            hb_live = mterm >= term
            hb_same_follower = (role == 0) & (mterm == term)
            hb_adopt = hb_live & ~hb_same_follower
            hb_row = jnp.stack(
                [
                    zero,
                    mterm,
                    jnp.where(mterm == term, voted, zero),
                    zero,
                ]
            )
            hb_row = jnp.where(hb_adopt, hb_row, row)
            hb_set = jnp.where(hb_live, u(1), zero)

            is_rv = kind == self.K_REQUEST_VOTE
            is_v = kind == self.K_VOTE
            row_out = jnp.where(is_rv, rv_row, jnp.where(is_v, v_row, hb_row))
            sends = jnp.where(is_rv, rv_sends, jnp.where(is_v, v_sends, no_sends))
            set_bits = jnp.where(is_rv | is_v, zero, hb_set)
            cancel_bits = jnp.where(is_v, v_cancel, zero)
            changed = jnp.where(
                is_rv, rv_changed, jnp.where(is_v, v_changed | v_wins, hb_adopt)
            )
            return row_out, sends, set_bits, cancel_bits, changed

        return [on_msg]

    def on_timeout_branches(self, model):
        import jax
        import jax.numpy as jnp

        n = self.n
        maj = majority(n)
        u = jnp.uint32

        def on_timeout(me, row, bit):
            term1 = row[1] + 1
            meu = me.astype(u)
            votes1 = u(1) << meu
            wins = jax.lax.population_count(votes1) >= maj  # single-node only
            row_out = jnp.stack(
                [jnp.where(wins, u(2), u(1)), term1, meu + 1, votes1]
            )
            sends = jnp.where(
                wins,
                self._no_sends(),
                self._broadcast(me, u(self.K_REQUEST_VOTE), term1),
            )
            # Host: set_timer first, cancel on self-election — cancel wins.
            set_bits = u(1)
            cancel_bits = jnp.where(wins, u(1), u(0))
            return row_out, sends, set_bits, cancel_bits, jnp.bool_(True)

        return [on_timeout]

    # -- traceable symmetry hooks ------------------------------------------

    def rewrite_actor_row(self, model, row, old_to_new):
        """``voted_for`` (stored +1, 0 = None) maps through the permutation;
        the ``votes`` bitmask moves bit ``b`` to bit ``old_to_new[b]``.
        Messages carry no ids (the envelope src is the vote's identity).
        The shift is masked to stay defined when ``old_to_new`` carries WL
        refinement colors (arbitrary uint32 names) instead of a true
        permutation — a no-op for real permutations (ids < n <= 32), and
        under colors the bitmask becomes a commutative digest of the
        voters' (masked) colors, which is all refinement needs."""
        import jax.numpy as jnp

        o2n = old_to_new.astype(jnp.uint32)
        voted = row[2]
        safe = jnp.where(voted == 0, jnp.uint32(0), voted - 1)
        new_voted = jnp.where(voted == 0, voted, o2n[safe] + 1)
        bits = (row[3] >> jnp.arange(self.n, dtype=jnp.uint32)) & jnp.uint32(1)
        new_votes = (bits << (o2n & jnp.uint32(31))).sum(dtype=jnp.uint32)
        return row.at[2].set(new_voted).at[3].set(new_votes)

    # -- traceable model hooks ---------------------------------------------

    def packed_conditions(self, model):
        import jax.numpy as jnp

        n = self.n
        crashes = bool(model._max_crashes)

        def live(state):
            if crashes:
                return state["crashed"] == 0
            return jnp.ones((n,), bool)

        def election_safety(state):
            role = state["rows"][:, 0]
            term = state["rows"][:, 1]
            lead = (role == 2) & live(state)
            pair = (
                lead[:, None]
                & lead[None, :]
                & (term[:, None] == term[None, :])
                & (jnp.arange(n)[:, None] < jnp.arange(n)[None, :])
            )
            return ~pair.any()

        def leader_elected(state):
            return ((state["rows"][:, 0] == 2) & live(state)).any()

        return [election_safety, leader_elected, leader_elected]

    def packed_within_boundary(self, model, state):
        return (state["rows"][:, 1] <= model.cfg.max_term).all()

    def packed_row_within_boundary(self, model, row):
        # Per-row decomposition of the term cap above (fps path contract).
        return row[1] <= model.cfg.max_term


@dataclass
class RaftModelCfg:
    server_count: int = 5
    max_term: int = 2
    lossy: bool = True
    max_crashes: int = 0
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        n = self.server_count
        model = PackedActorModel(
            codec=RaftPackedCodec(n), cfg=self, init_history=None
        )
        # Distinct-envelope upper bound: 3 message kinds × directed pairs ×
        # live terms (boundary-pruned states keep message terms ≤ max_term).
        model.with_envelope_capacity(max(8, 3 * n * (n - 1) * self.max_term))
        for i in range(n):
            model.actor(RaftActor(model_peers(i, n)))

        def election_safety(_model, state):
            leaders = [
                s.term
                for s, crashed in zip(state.actor_states, state.crashed)
                if not crashed and s.role == LEADER
            ]
            return len(leaders) == len(set(leaders))

        def leader_elected(_model, state):
            # Crashed leaders don't count (consistent with election_safety):
            # a dead leader's cluster is leaderless.
            return any(
                s.role == LEADER
                for s, crashed in zip(state.actor_states, state.crashed)
                if not crashed
            )

        max_term = self.max_term
        return (
            model.init_network(self.network)
            .lossy_network(self.lossy)
            .max_crashes(self.max_crashes)
            .within_boundary_fn(
                lambda _cfg, state: all(
                    s.term <= max_term for s in state.actor_states
                )
            )
            .property(Expectation.ALWAYS, "election safety", election_safety)
            .property(Expectation.SOMETIMES, "leader elected", leader_elected)
            .property(Expectation.EVENTUALLY, "stable leader", leader_elected)
        )
