"""Raft leader election, model-checked with lossy networks and symmetry.

A new example required by the BASELINE configs (the reference ships no Raft
example; the actor/builder idioms follow ``/root/reference/examples/paxos.rs``).
Scope is the election subprotocol: election timers fire nondeterministically
(every timing interleaving is explored), candidates solicit votes, a majority
quorum elects a leader which announces itself by heartbeat.

Checked properties:

- ``always "election safety"`` — at most one leader per term (Raft paper §5.2
  invariant); holds under message loss, duplication, and reordering.
- ``sometimes "leader elected"`` — a leader exists (witness the protocol can
  make progress).
- ``eventually "stable leader"`` — *intentionally falsifiable*: repeated
  split votes (or total message loss on lossy networks) can exhaust the term
  boundary with no leader elected, and the checker reports the
  counterexample trace; liveness in Raft requires randomized timeouts, which
  a model checker deliberately explores the adversarial schedules of.

The term bound (``max_term``) is the state-space boundary knob, like the
reference's ``max_nat`` ping-pong bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    model_peers,
    model_timeout,
)
from ..core.model import Expectation

FOLLOWER, CANDIDATE, LEADER = "Follower", "Candidate", "Leader"
ELECTION = "Election"


def majority(cluster_size: int) -> int:
    return cluster_size // 2 + 1


# Messages (no embedded Ids — src carries the sender, keeping symmetry
# rewriting to the envelope level):
#   ("RequestVote", term)
#   ("Vote", term)            -- a granted vote (denials are silent)
#   ("Heartbeat", term)


@dataclass(frozen=True)
class RaftState:
    role: str
    term: int
    voted_for: Optional[Id]
    votes: FrozenSet[Id]


class RaftActor(Actor):
    def __init__(self, peer_ids: List[Id]):
        self.peer_ids = peer_ids

    def name(self) -> str:
        return "Raft Server"

    def _cluster_size(self) -> int:
        return len(self.peer_ids) + 1

    def on_start(self, id: Id, o: Out) -> RaftState:
        o.set_timer(ELECTION, model_timeout())
        return RaftState(role=FOLLOWER, term=0, voted_for=None, votes=frozenset())

    def on_timeout(self, id: Id, state: RaftState, timer, o: Out):
        if timer != ELECTION:
            return None
        # Start (or restart, on split votes) an election.
        o.set_timer(ELECTION, model_timeout())
        term = state.term + 1
        votes = frozenset([id])
        if len(votes) >= majority(self._cluster_size()):
            # Single-node cluster: the self-vote is already a majority.
            o.cancel_timer(ELECTION)
            return RaftState(role=LEADER, term=term, voted_for=id, votes=votes)
        o.broadcast(self.peer_ids, ("RequestVote", term))
        return RaftState(role=CANDIDATE, term=term, voted_for=id, votes=votes)

    def on_msg(self, id: Id, state: RaftState, src: Id, msg, o: Out):
        kind, term = msg[0], msg[1]
        if kind == "RequestVote":
            if term > state.term:
                # Newer term: adopt it as a follower and grant the vote.
                o.send(src, ("Vote", term))
                return RaftState(
                    role=FOLLOWER, term=term, voted_for=src, votes=frozenset()
                )
            if (
                term == state.term
                and state.role == FOLLOWER
                and state.voted_for in (None, src)
            ):
                o.send(src, ("Vote", term))
                if state.voted_for == src:
                    return None  # duplicate request, vote resent
                return RaftState(
                    role=FOLLOWER,
                    term=term,
                    voted_for=src,
                    votes=state.votes,
                )
            return None  # stale term or vote already cast: deny silently

        if kind == "Vote":
            if state.role != CANDIDATE or term != state.term:
                return None  # stale vote (e.g. from a previous election)
            votes = state.votes | {src}
            if len(votes) >= majority(self._cluster_size()):
                o.cancel_timer(ELECTION)
                o.broadcast(self.peer_ids, ("Heartbeat", state.term))
                return RaftState(
                    role=LEADER,
                    term=state.term,
                    voted_for=state.voted_for,
                    votes=votes,
                )
            if votes == state.votes:
                return None  # duplicate vote
            return RaftState(
                role=CANDIDATE,
                term=state.term,
                voted_for=state.voted_for,
                votes=votes,
            )

        if kind == "Heartbeat":
            if term < state.term:
                return None  # stale leader
            if state.role == FOLLOWER and term == state.term:
                # Already following this term's leader; renewing the election
                # timer alone would be a no-op-with-timer (pruned).
                o.set_timer(ELECTION, model_timeout())
                return None
            o.set_timer(ELECTION, model_timeout())
            return RaftState(
                role=FOLLOWER,
                term=term,
                voted_for=state.voted_for if term == state.term else None,
                votes=frozenset(),
            )

        return None


@dataclass
class RaftModelCfg:
    server_count: int = 5
    max_term: int = 2
    lossy: bool = True
    max_crashes: int = 0
    network: Network = field(
        default_factory=Network.new_unordered_nonduplicating
    )

    def into_model(self) -> ActorModel:
        model = ActorModel(cfg=self, init_history=None)
        for i in range(self.server_count):
            model.actor(RaftActor(model_peers(i, self.server_count)))

        def election_safety(_model, state):
            leaders = [
                s.term
                for s, crashed in zip(state.actor_states, state.crashed)
                if not crashed and s.role == LEADER
            ]
            return len(leaders) == len(set(leaders))

        def leader_elected(_model, state):
            # Crashed leaders don't count (consistent with election_safety):
            # a dead leader's cluster is leaderless.
            return any(
                s.role == LEADER
                for s, crashed in zip(state.actor_states, state.crashed)
                if not crashed
            )

        max_term = self.max_term
        return (
            model.init_network(self.network)
            .lossy_network(self.lossy)
            .max_crashes(self.max_crashes)
            .within_boundary_fn(
                lambda _cfg, state: all(
                    s.term <= max_term for s in state.actor_states
                )
            )
            .property(Expectation.ALWAYS, "election safety", election_safety)
            .property(Expectation.SOMETIMES, "leader elected", leader_elected)
            .property(Expectation.EVENTUALLY, "stable leader", leader_elected)
        )
