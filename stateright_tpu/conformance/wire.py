"""Conformance wire format v1: recorded executions as JSONL frames.

This is the ingestion leg of the conformance plane (ROADMAP item 5):
users upload what their *real* system did — operation histories from
client libraries and event traces from deployment logs — and the
service audits them against the reference semantics / the packed model.
Uploads are hostile by construction (torn writes, truncated files,
version skew, hand-edited JSON), so every frame is validated and every
rejection is an **honest refusal** with a line number and a reason —
never a silent drop, never a crash. A refused frame still gets a
verdict (``"refused"``) so batch accounting always sums to the upload.

One JSON object per line. Two frame kinds share a common envelope::

    {"v": 1, "kind": "history", "id": "h0",
     "semantics": "linearizability" | "sequential",
     "spec": {"type": "register", "default": "a"} | {"type": "vec"},
     "events": [["invoke", 0, ["Write", "b"]], ["return", 0, ["WriteOk"]],
                ["invoke", 1, ["Read"]], ["return", 1, ["ReadOk", "b"]]],
     "meta": {...}}

    {"v": 1, "kind": "trace", "id": "t0",
     "model": "2pc", "model_args": {"rm_count": 3},
     "init": 0, "actions": [3, 1, 4, 1], "meta": {...}}

- ops/returns are the tagged tuples of ``semantics/`` rendered as JSON
  arrays (``("Write", "b")`` -> ``["Write", "b"]``); register and vec
  payload values must be single-character strings (the packed codecs
  carry them as ``ord``/``chr`` words).
- ``meta`` is free-form and round-trips untouched — corpus generators
  label expectations there (``{"expect": "divergent",
  "divergence_index": 3}``) and the parity tests read them back.
- unknown *extra* keys are tolerated (forward compatibility); unknown
  ``v``/``kind``/``semantics``/``spec.type`` are refused (a frame we
  cannot interpret must not be guessed at).

``decode_lines`` is the one entry point; ``bucket_key`` assigns each
decoded record to a fixed-shape lane bucket (histories: exact
``(spec, semantics, threads, max ops/thread)``; traces: ``(model, args,
next-pow2 length)``) so batches vmap over identical static shapes.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

WIRE_VERSION = 1

HISTORY_SEMANTICS = ("linearizability", "sequential")
SPEC_TYPES = ("register", "vec")

# op tag -> the return tag a completed op must carry. A mismatched pair
# (e.g. Push answered by ReadOk) is not a *failing* history — it is a
# frame the reference semantics cannot even type, so it is refused at
# the wire rather than laundered into an "inconsistent" verdict.
_RET_TAG = {
    "Write": "WriteOk",
    "Read": "ReadOk",
    "Push": "PushOk",
    "Pop": "PopOk",
    "Len": "LenOk",
}
_REGISTER_OPS = ("Write", "Read")
_VEC_OPS = ("Push", "Pop", "Len")


class WireRefusal(ValueError):
    """One refused frame: ``line`` (1-based), ``reason``; raised only in
    strict mode — batch decoding collects these as records instead."""

    def __init__(self, line: int, reason: str, frame_id=None):
        super().__init__(f"line {line}: {reason}")
        self.line = line
        self.reason = reason
        self.frame_id = frame_id

    def as_record(self) -> dict:
        return {
            "line": self.line,
            "reason": self.reason,
            "id": self.frame_id,
        }


def _is_char(v) -> bool:
    return isinstance(v, str) and len(v) == 1


def _check_op(line: int, op, allowed, fid) -> Tuple[str, Optional[str]]:
    """Validates one op array -> (tag, value-or-None)."""
    if not isinstance(op, list) or not op or not isinstance(op[0], str):
        raise WireRefusal(line, f"malformed op {op!r}", fid)
    tag = op[0]
    if tag not in allowed:
        raise WireRefusal(
            line, f"op {tag!r} not valid for this spec", fid
        )
    if tag in ("Write", "Push"):
        if len(op) != 2 or not _is_char(op[1]):
            raise WireRefusal(
                line,
                f"{tag} payload must be one single-character string, "
                f"got {op[1:]!r}",
                fid,
            )
        return tag, op[1]
    if len(op) != 1:
        raise WireRefusal(line, f"{tag} takes no payload, got {op!r}", fid)
    return tag, None


def _check_ret(line: int, op_tag: str, ret, fid):
    """Validates one return array against its op -> normalized payload:
    Write/Push -> None; Read/Pop-Some -> char; Pop-None -> None marker;
    Len -> int."""
    want = _RET_TAG[op_tag]
    if not isinstance(ret, list) or not ret or ret[0] != want:
        raise WireRefusal(
            line, f"return for {op_tag} must be {want}, got {ret!r}", fid
        )
    if want in ("WriteOk", "PushOk"):
        if len(ret) != 1:
            raise WireRefusal(line, f"{want} takes no payload", fid)
        return None
    if want == "ReadOk":
        if len(ret) != 2 or not _is_char(ret[1]):
            raise WireRefusal(
                line, f"ReadOk payload must be one char, got {ret[1:]!r}",
                fid,
            )
        return ret[1]
    if want == "PopOk":
        # PopOk(None) | PopOk(("Some", v)) — JSON: ["PopOk", null] /
        # ["PopOk", ["Some", "v"]].
        if len(ret) != 2:
            raise WireRefusal(line, "PopOk needs exactly one payload", fid)
        if ret[1] is None:
            return ("none",)
        if (
            isinstance(ret[1], list) and len(ret[1]) == 2
            and ret[1][0] == "Some" and _is_char(ret[1][1])
        ):
            return ("some", ret[1][1])
        raise WireRefusal(
            line,
            f'PopOk payload must be null or ["Some", <char>], '
            f"got {ret[1]!r}",
            fid,
        )
    # LenOk
    if len(ret) != 2 or not isinstance(ret[1], int) or ret[1] < 0:
        raise WireRefusal(
            line, f"LenOk payload must be a non-negative int, got "
            f"{ret[1:]!r}", fid,
        )
    return ret[1]


def _decode_history(line: int, obj: dict) -> dict:
    fid = obj.get("id")
    semantics = obj.get("semantics")
    if semantics not in HISTORY_SEMANTICS:
        raise WireRefusal(
            line,
            f"unknown semantics {semantics!r} (expected one of "
            f"{list(HISTORY_SEMANTICS)})",
            fid,
        )
    spec = obj.get("spec")
    if not isinstance(spec, dict) or spec.get("type") not in SPEC_TYPES:
        raise WireRefusal(
            line,
            f"unknown spec {spec!r} (expected type in {list(SPEC_TYPES)})",
            fid,
        )
    spec_type = spec["type"]
    default = None
    if spec_type == "register":
        default = spec.get("default", "a")
        if not _is_char(default):
            raise WireRefusal(
                line,
                f"register default must be one single-character string, "
                f"got {default!r}",
                fid,
            )
    allowed = _REGISTER_OPS if spec_type == "register" else _VEC_OPS
    events_in = obj.get("events")
    if not isinstance(events_in, list):
        raise WireRefusal(line, "history frame is missing 'events'", fid)
    events = []
    in_flight: Dict[int, str] = {}
    for ev in events_in:
        if (
            not isinstance(ev, list) or len(ev) != 3
            or ev[0] not in ("invoke", "return")
            or not isinstance(ev[1], int) or ev[1] < 0
        ):
            raise WireRefusal(line, f"malformed event {ev!r}", fid)
        etype, tid, payload = ev
        if etype == "invoke":
            tag, value = _check_op(line, payload, allowed, fid)
            # NOTE: a double-invoke / orphan return is NOT refused here:
            # the host testers accept exactly one such event (marking
            # the history invalid forever) and refuse everything after
            # it ("Earlier history was invalid"), so the audit must see
            # the latching event to stay bit-identical — but events past
            # it are unreachable by the reference semantics (and
            # untypeable: the latch broke the op/return pairing), so
            # decoding stops there.
            if tid in in_flight:
                events.append(("invoke", tid, tag, value))
                break
            in_flight[tid] = tag
            events.append(("invoke", tid, tag, value))
        else:
            op_tag = in_flight.pop(tid, None)
            if op_tag is None:
                # Orphan return: latches exactly like a double invoke;
                # the payload is never interpreted.
                if not isinstance(payload, list) or not payload:
                    raise WireRefusal(
                        line, f"malformed return {payload!r}", fid
                    )
                events.append(("return", tid, None, None))
                break
            value = _check_ret(line, op_tag, payload, fid)
            events.append(("return", tid, op_tag, value))
    return {
        "kind": "history",
        "id": fid if isinstance(fid, str) else f"line{line}",
        "semantics": semantics,
        "spec": spec_type,
        "default": default,
        "events": events,
        "meta": obj.get("meta") or {},
    }


def _decode_trace(line: int, obj: dict) -> dict:
    fid = obj.get("id")
    model = obj.get("model")
    if not isinstance(model, str) or not model:
        raise WireRefusal(line, "trace frame is missing 'model'", fid)
    args = obj.get("model_args") or {}
    if not isinstance(args, dict):
        raise WireRefusal(
            line, f"model_args must be an object, got {args!r}", fid
        )
    init = obj.get("init", 0)
    if not isinstance(init, int) or init < 0:
        raise WireRefusal(
            line, f"init must be a non-negative int, got {init!r}", fid
        )
    actions = obj.get("actions")
    if (
        not isinstance(actions, list) or not actions
        or not all(isinstance(a, int) and a >= 0 for a in actions)
    ):
        raise WireRefusal(
            line, "actions must be a non-empty list of non-negative "
            "action ids", fid,
        )
    return {
        "kind": "trace",
        "id": fid if isinstance(fid, str) else f"line{line}",
        "model": model,
        "model_args": args,
        "init": init,
        "actions": list(actions),
        "meta": obj.get("meta") or {},
    }


def decode_frame(line: int, text: str) -> dict:
    """One wire line -> one decoded record; raises ``WireRefusal``."""
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as e:
        # Torn frame: a killed writer tears the last line mid-object.
        raise WireRefusal(line, f"torn/unparseable frame: {e}") from e
    if not isinstance(obj, dict):
        raise WireRefusal(line, f"frame must be an object, got {obj!r}")
    fid = obj.get("id")
    v = obj.get("v")
    if v != WIRE_VERSION:
        raise WireRefusal(
            line, f"unknown wire version {v!r} (this decoder speaks "
            f"v{WIRE_VERSION})", fid,
        )
    kind = obj.get("kind")
    if kind == "history":
        return _decode_history(line, obj)
    if kind == "trace":
        return _decode_trace(line, obj)
    raise WireRefusal(
        line, f"unknown frame kind {kind!r} (expected 'history'/'trace')",
        fid,
    )


def decode_lines(
    lines: Sequence[str], strict: bool = False
) -> Tuple[List[dict], List[dict]]:
    """Decodes a whole upload -> ``(records, refusals)``.

    ``strict=True`` raises the first ``WireRefusal`` instead (the HTTP
    admission path: a 400 with the offending line beats accepting a
    batch whose accounting cannot match the upload)."""
    records: List[dict] = []
    refusals: List[dict] = []
    for n, text in enumerate(lines, start=1):
        text = text.strip()
        if not text:
            continue
        try:
            records.append(decode_frame(n, text))
        except WireRefusal as r:
            if strict:
                raise
            refusals.append(r.as_record())
    return records, refusals


def encode_record(rec: dict) -> str:
    """Decoded record -> one wire line (the corpus writers' inverse).
    Accepts both decoded records and raw frame dicts."""
    if "v" in rec:  # already a raw frame
        return json.dumps(rec, sort_keys=True)
    if rec["kind"] == "trace":
        frame = {
            "v": WIRE_VERSION, "kind": "trace", "id": rec["id"],
            "model": rec["model"], "model_args": rec["model_args"],
            "init": rec["init"], "actions": rec["actions"],
        }
        if rec.get("meta"):
            frame["meta"] = rec["meta"]
        return json.dumps(frame, sort_keys=True)
    events = []
    for etype, tid, tag, value in rec["events"]:
        if etype == "invoke":
            op = [tag] if value is None else [tag, value]
            events.append(["invoke", tid, op])
        else:
            events.append(["return", tid, _encode_ret(tag, value)])
    frame = {
        "v": WIRE_VERSION, "kind": "history", "id": rec["id"],
        "semantics": rec["semantics"],
        "spec": (
            {"type": "register", "default": rec["default"]}
            if rec["spec"] == "register" else {"type": "vec"}
        ),
        "events": events,
    }
    if rec.get("meta"):
        frame["meta"] = rec["meta"]
    return json.dumps(frame, sort_keys=True)


def _encode_ret(op_tag, value):
    if op_tag is None:
        return ["OrphanReturn"]
    want = _RET_TAG[op_tag]
    if want in ("WriteOk", "PushOk"):
        return [want]
    if want == "ReadOk":
        return ["ReadOk", value]
    if want == "PopOk":
        return ["PopOk", None if value == ("none",) else ["Some", value[1]]]
    return ["LenOk", value]


# -- shape bucketing --------------------------------------------------------


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def history_shape(rec: dict) -> Tuple[int, int]:
    """(threads C, max ops/thread O) of one decoded history — EXACT (not
    padded) for the history buckets: the packed predicates' verdicts are
    only host-bit-identical at the history's true (C, O), because
    phantom threads/slots change nothing but phantom *capacity* errors
    would. Threads are the dense sorted set of ids that ever appear."""
    counts: Dict[int, int] = {}
    for etype, tid, _tag, _value in rec["events"]:
        if etype == "invoke":
            counts[tid] = counts.get(tid, 0) + 1
        else:
            counts.setdefault(tid, counts.get(tid, 0))
    C = max(1, len(counts))
    O = max([1] + list(counts.values()))
    return C, O


def bucket_key(rec: dict) -> tuple:
    """The fixed-shape lane bucket one record batches into. Records in
    one bucket share every static shape, so a bucket is one vmapped
    dispatch (and one AOT warm-pool entry)."""
    if rec["kind"] == "trace":
        return (
            "trace",
            rec["model"],
            tuple(sorted((k, repr(v)) for k, v in rec["model_args"].items())),
            _next_pow2(len(rec["actions"])),
        )
    C, O = history_shape(rec)
    # The register audit kernel bakes ord(default) into the traced
    # predicate, so two histories with the same shape but different
    # defaults must NOT batch into one dispatch — the second would be
    # audited against the wrong initial register value. Vec histories
    # have no default; key None so they still share a bucket.
    default = rec["default"] if rec["spec"] == "register" else None
    return ("history", rec["spec"], rec["semantics"], C, O, default)


def bucket_records(records: Sequence[dict]) -> Dict[tuple, List[dict]]:
    """Stable-order bucketing: records keep upload order inside their
    bucket and buckets keep first-appearance order (verdict order must
    be a pure function of the upload, not of dict iteration)."""
    out: Dict[tuple, List[dict]] = {}
    for rec in records:
        out.setdefault(bucket_key(rec), []).append(rec)
    return out
