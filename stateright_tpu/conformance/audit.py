"""Batched device consistency auditing over uploaded operation histories.

``semantics/packed_linearizability.py`` proved the shape: a bounded
history packs into a fixed-width u32 vector and the Wing&Gong search
becomes a static-shaped traceable predicate. This module generalizes it
from one in-wave register history to the conformance plane's workload —
a vmapped *batch* of uploaded histories per (spec, semantics, C, O,
default) shape bucket (``default`` is the register's initial value —
the kernel bakes it into the traced predicate, so it is part of the
bucket identity; None for vec):

- **register** histories ride ``PackedRegisterLinearizability``
  unchanged: ingestion drives the host ``LinearizabilityTester`` (which
  captures the dense real-time constraint words) and ``pack``s it; the
  device predicate is the consumption-vector DP, with
  ``real_time=False`` for the sequential-consistency buckets.
- **vec** (stack) histories get their own packed codec here
  (``PackedVecHistory``): per-thread slots ``[kind, value, ret_kind,
  ret_value, constraint[C]]`` (kinds 1=Push/2=Pop/3=Len) and a
  lane-grid predicate — every program-order interleaving × every
  in-flight inclusion replays the stack semantics with masks. The DP's
  value-bitmask trick is register-specific (a register IS its last
  write); a stack needs the actual LIFO replay, and the lane grid is
  exactly ``predicate_lanes`` with a stack register file.

Every verdict is gated on the host testers: ``host_is_consistent`` is
the oracle the parity suite (and the checker's seed-corpus gate) diffs
against, bit-for-bit. Histories the bounded codecs cannot represent
(register value universe > 31 ops, vec lane grids past the static
bound) — or whose kernels would be pathological to *compile* (the
register DP transition graph past ``MAX_REGISTER_DP_TRANSITIONS``) —
are **refused honestly**: ``pack_history`` returns a reason instead of
a wrong verdict or a minutes-long XLA stall.

Kernels are cached process-globally per bucket key (the same economics
as the checkers' shared AOT cache: a resident service re-audits a hot
shape without retracing).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..semantics.linearizability import LinearizabilityTester
from ..semantics.packed_linearizability import (
    PackedRegisterLinearizability,
    _interleavings,
)
from ..semantics.register import READ, ReadOk, Register, Write, WRITE_OK
from ..semantics.sequential_consistency import SequentialConsistencyTester
from ..semantics.vec import LEN, LenOk, POP, PopOk, Push, PUSH_OK, VecSpec
from .wire import history_shape

# Static bound on the vec lane grid (interleavings x 2^C inclusion
# masks). C=2,O=2 -> 24 lanes; C=3,O=2 -> 720; C=2,O=4 -> 280. Past
# this the unrolled kernel stops being a sane trace — refuse, honestly.
MAX_VEC_LANES = 4096

# Static bound on the register DP's unrolled transition graph,
# ``(O+1)^C * C``. The DP's python loop unrolls one mask update per
# transition, and XLA's compile time is sharply superlinear in that
# graph: C=2,O=2 (18) ~0.7s, C=3,O=2 (81) ~10s, C=3,O=3 (192) wedges
# the compiler for minutes. A hostile upload must not be able to park
# the service inside XLA — past this bound, refuse honestly (same
# economics as MAX_VEC_LANES; the 31-op value-mask bound below is a
# correctness bound, this one is a compile-sanity bound).
MAX_REGISTER_DP_TRANSITIONS = 96


def _thread_ids(rec: dict) -> List[int]:
    ids = set()
    for _etype, tid, _tag, _value in rec["events"]:
        ids.add(tid)
    return sorted(ids) or [0]


def _wire_op(tag: str, value):
    if tag == "Write":
        return Write(value)
    if tag == "Read":
        return READ
    if tag == "Push":
        return Push(value)
    if tag == "Pop":
        return POP
    return LEN


def _wire_ret(tag: str, value):
    if tag == "Write":
        return WRITE_OK
    if tag == "Read":
        return ReadOk(value)
    if tag == "Push":
        return PUSH_OK
    if tag == "Pop":
        return PopOk(None if value == ("none",) else ("Some", value[1]))
    return LenOk(value)


def drive_tester(tester, events) -> None:
    """Replays decoded wire events into a host-style tester, stopping at
    the first invalidating event exactly as a host client would (the
    testers raise AND latch ``is_valid_history=False``; feeding further
    events would raise "Earlier history was invalid")."""
    for etype, tid, tag, value in events:
        try:
            if etype == "invoke":
                tester.on_invoke(tid, _wire_op(tag, value))
            elif tag is None:  # orphan return: payload untypeable
                tester.on_return(tid, ("OrphanReturn",))
            else:
                tester.on_return(tid, _wire_ret(tag, value))
        except ValueError:
            return


def host_is_consistent(rec: dict) -> bool:
    """THE parity oracle: the host tester's verdict for one decoded
    history record. Every device verdict is gated on agreeing with this
    bit-for-bit."""
    spec = (
        Register(rec["default"]) if rec["spec"] == "register" else VecSpec()
    )
    tester = (
        LinearizabilityTester(spec)
        if rec["semantics"] == "linearizability"
        else SequentialConsistencyTester(spec)
    )
    drive_tester(tester, rec["events"])
    return tester.is_consistent()


# -- vec (stack) packed codec ----------------------------------------------


class PackedVecHistory:
    """Fixed-width packing + traceable predicate for bounded stack
    histories (``VecSpec``): C threads x at most O ops each.

    Layout (all u32): ``vec[0]`` = is_valid_history; thread ``c`` block
    = count word + O slots ``[kind, value, ret_kind, ret_value,
    constraint[C]]``. Kinds: 0 absent, 1 Push (value = pushed char),
    2 Pop (completed: ret_kind 1=PopOk(None), 2=PopOk(Some ret_value)),
    3 Len (completed: ret_value = returned length). ``constraint[p]``
    is peer ``p``'s completed count at invoke time (dense
    ``completed_map``) — ignored under ``real_time=False``.
    """

    SW = 4  # kind, value, ret_kind, ret_value (+ C constraint words)

    def __init__(self, C: int, O: int):
        self.C = C
        self.O = O
        self.TW = 1 + O * (self.SW + C)
        self.width = 1 + C * self.TW
        # Bound check FIRST, arithmetically: the interleaving count is
        # the multinomial (C*O)!/(O!)^C, so a hostile shape (say 5x5 ->
        # ~6e14 sequences) must be refused before _interleavings — a
        # full recursive enumeration — ever runs, or the refusal itself
        # parks the worker in unbounded CPU/memory.
        n_seqs = math.factorial(C * O) // (math.factorial(O) ** C)
        self.lanes = n_seqs * (1 << C)
        if self.lanes > MAX_VEC_LANES:
            raise ValueError(
                f"vec history lane grid {self.lanes} exceeds "
                f"{MAX_VEC_LANES} ({C} threads x {O} ops); split the "
                "history or audit it on the host"
            )
        self._seqs = _interleavings(C, O)

    def _slot(self, c: int, j: int) -> int:
        return 1 + c * self.TW + 1 + j * (self.SW + self.C)

    def pack(self, events, thread_ids: Sequence[int]) -> np.ndarray:
        """Decoded wire events -> packed vector, mirroring the host
        testers' recording semantics exactly (double-invoke / orphan
        return latch invalid and freeze)."""
        C, O = self.C, self.O
        dense = {t: c for c, t in enumerate(thread_ids)}
        out = np.zeros((self.width,), np.uint32)
        out[0] = 1
        counts = [0] * C
        inflight: Dict[int, int] = {}  # dense thread -> slot index
        for etype, tid, tag, value in events:
            c = dense[tid]
            if etype == "invoke":
                if c in inflight or counts[c] >= O:
                    out[0] = 0
                    return out
                j = counts[c]
                b = self._slot(c, j)
                out[b] = {"Push": 1, "Pop": 2, "Len": 3}[tag]
                out[b + 1] = ord(value) if tag == "Push" else 0
                for p in range(C):
                    out[b + self.SW + p] = counts[p] if p != c else 0
                inflight[c] = j
            else:
                if c not in inflight:
                    out[0] = 0
                    return out
                j = inflight.pop(c)
                b = self._slot(c, j)
                if tag == "Pop":
                    if value == ("none",):
                        out[b + 2] = 1
                    else:
                        out[b + 2] = 2
                        out[b + 3] = ord(value[1])
                elif tag == "Len":
                    # The wire admits any non-negative LenOk, but the
                    # stack can never hold more than C*O entries, so any
                    # larger payload is equally unsatisfiable — clamp to
                    # C*O+1 rather than overflow the u32 slot (the host
                    # oracle reports such a history inconsistent, not a
                    # worker error).
                    out[b + 3] = min(int(value), C * O + 1)
                counts[c] += 1
                out[1 + c * self.TW] = counts[c]
        return out

    def predicate(self, real_time: bool = True):
        """``fn(hist) -> bool``: True iff a serialization exists. Lane
        grid = interleavings x in-flight inclusion; each lane replays
        the stack with a fixed-size register file (size M = C*O, the
        push upper bound) and masks, like
        ``PackedRegisterLinearizability.predicate_lanes`` with LIFO
        state instead of a scalar value."""
        import jax
        import jax.numpy as jnp

        C, O, SW = self.C, self.O, self.SW
        M = C * O
        seq_t, seq_j = self._seqs
        S = seq_t.shape[0]
        from itertools import product as _product

        incs = np.array(list(_product([0, 1], repeat=C)), np.uint32)
        K = incs.shape[0]
        SEQ_T = jnp.asarray(np.repeat(seq_t, K, axis=0))
        SEQ_J = jnp.asarray(np.repeat(seq_j, K, axis=0))
        INCS = jnp.asarray(np.tile(incs, (S, 1)))

        def split(hist):
            valid = hist[0]
            body = hist[1:].reshape(C, self.TW)
            counts = body[:, 0]
            slots = body[:, 1:].reshape(C, O, SW + C)
            return valid, counts, slots

        def lane(seq_t_row, seq_j_row, inc, counts, slots):
            stack = jnp.zeros((M,), jnp.uint32)
            sp = jnp.int32(0)
            ok = jnp.bool_(True)
            consumed = jnp.zeros((C,), jnp.uint32)
            for pos in range(M):  # static unroll; M is small
                t = seq_t_row[pos]
                j = seq_j_row[pos]
                kind = slots[t, j, 0]
                value = slots[t, j, 1]
                ret_kind = slots[t, j, 2]
                ret_value = slots[t, j, 3]
                constr = slots[t, j, SW:]
                completed = j.astype(jnp.uint32) < counts[t]
                inflight = (
                    (j.astype(jnp.uint32) == counts[t])
                    & (kind != 0)
                    & (inc[t] == 1)
                )
                present = completed | inflight
                if real_time:
                    ok &= ~present | (consumed >= constr).all()
                # Stack semantics (host ``VecSpec.is_valid_step`` =
                # invoke-and-compare): completed Pops/Lens must observe
                # the current stack; in-flight ops generate their
                # return (always valid) but still mutate.
                top = stack[jnp.clip(sp - 1, 0, M - 1)]
                pop_ok = jnp.where(
                    ret_kind == 2,
                    (sp > 0) & (top == ret_value),
                    sp == 0,
                )
                step_ok = jnp.where(
                    kind == 2, pop_ok,
                    jnp.where(
                        kind == 3, sp.astype(jnp.uint32) == ret_value,
                        jnp.bool_(True),
                    ),
                )
                ok &= ~(present & completed) | step_ok
                do_push = present & (kind == 1)
                do_pop = present & (kind == 2) & (sp > 0)
                stack = stack.at[jnp.clip(sp, 0, M - 1)].set(
                    jnp.where(do_push, value, stack[jnp.clip(sp, 0, M - 1)])
                )
                sp = sp + do_push.astype(jnp.int32) \
                    - do_pop.astype(jnp.int32)
                consumed = consumed.at[t].add(present.astype(jnp.uint32))
            return ok

        def fn(hist):
            valid, counts, slots = split(hist)
            ok = jax.vmap(
                lambda st, sj, m: lane(st, sj, m, counts, slots)
            )(SEQ_T, SEQ_J, INCS)
            return (valid == 1) & ok.any()

        return fn


# -- packing + batched kernels ---------------------------------------------


def pack_history(rec: dict) -> Tuple[Optional[np.ndarray], Optional[str]]:
    """One decoded history -> ``(packed vector, None)`` or ``(None,
    refusal reason)`` when the bounded codec cannot represent it."""
    C, O = history_shape(rec)
    tids = _thread_ids(rec)
    if rec["spec"] == "register":
        if 1 + C * O > 32:
            return None, (
                f"register history too wide for the device DP "
                f"({C} threads x {O} ops = {C * O} ops; bound is 31)"
            )
        transitions = (O + 1) ** C * C
        if transitions > MAX_REGISTER_DP_TRANSITIONS:
            return None, (
                f"register DP graph too large to compile sanely "
                f"({C} threads x {O} ops -> {transitions} unrolled "
                f"transitions; bound is {MAX_REGISTER_DP_TRANSITIONS}); "
                "split the history or audit it on the host"
            )
        codec = PackedRegisterLinearizability(tids, O, rec["default"])
        # The Lin tester records the dense real-time constraints even
        # for SC buckets (the SC predicate just ignores them).
        tester = LinearizabilityTester(Register(rec["default"]))
        drive_tester(tester, rec["events"])
        return codec.pack(tester), None
    try:
        codec = PackedVecHistory(C, O)
    except ValueError as e:
        return None, str(e)
    return codec.pack(rec["events"], tids), None


_KERNELS: Dict[tuple, object] = {}
_KERNELS_LOCK = threading.Lock()


def audit_kernel(spec: str, semantics: str, C: int, O: int,
                 default: Optional[str] = None):
    """The jitted vmapped batch auditor for one shape bucket:
    ``fn(hists (B, width) u32) -> bool (B,)``. Cached process-globally —
    a resident service re-audits a hot bucket without retracing."""
    key = (spec, semantics, C, O, default)
    with _KERNELS_LOCK:
        fn = _KERNELS.get(key)
        if fn is not None:
            return fn
    import jax

    real_time = semantics == "linearizability"
    if spec == "register":
        codec = PackedRegisterLinearizability(
            list(range(C)), O, default or "a"
        )
        pred = codec.predicate(real_time=real_time)
    else:
        codec = PackedVecHistory(C, O)
        pred = codec.predicate(real_time=real_time)
    fn = jax.jit(jax.vmap(pred))
    with _KERNELS_LOCK:
        _KERNELS[key] = fn
    return fn


def clear_audit_kernels() -> None:
    """Test hook: drop the process-global kernel cache."""
    with _KERNELS_LOCK:
        _KERNELS.clear()


def audit_batch(records: Sequence[dict],
                lanes: Optional[int] = None) -> List[dict]:
    """Audits one shape bucket of decoded histories in one vmapped
    device dispatch. All records MUST share ``bucket_key`` (the checker
    guarantees it). Returns one verdict dict per record, in order:
    ``{"id", "kind": "history", "semantics", "consistent",
    "valid_history"}`` or ``{"id", "kind": "history", "refused": ...}``.

    ``lanes`` pads short batches to a fixed row count with inert
    all-zero vectors (``valid=0``; their verdicts are discarded) so a
    resident service reuses one jitted executable per bucket instead of
    retracing for every distinct chunk size.
    """
    if not records:
        return []
    C, O = history_shape(records[0])
    spec = records[0]["spec"]
    semantics = records[0]["semantics"]
    default = records[0].get("default")
    packed: List[np.ndarray] = []
    slots: List[Optional[int]] = []
    verdicts: List[Optional[dict]] = []
    for rec in records:
        vec, refusal = pack_history(rec)
        if refusal is not None:
            slots.append(None)
            verdicts.append(
                {"id": rec["id"], "kind": "history", "refused": refusal}
            )
        else:
            slots.append(len(packed))
            packed.append(vec)
            verdicts.append(None)
    if packed:
        fn = audit_kernel(spec, semantics, C, O, default)
        batch = np.stack(packed)
        if lanes is not None and batch.shape[0] < lanes:
            pad = np.zeros(
                (lanes - batch.shape[0], batch.shape[1]), np.uint32
            )
            batch = np.concatenate([batch, pad])
        out = np.asarray(fn(batch))[: len(packed)]
    else:
        out = np.zeros((0,), bool)
    for i, rec in enumerate(records):
        if verdicts[i] is not None:
            continue
        vec = packed[slots[i]]
        verdicts[i] = {
            "id": rec["id"],
            "kind": "history",
            "semantics": semantics,
            "consistent": bool(out[slots[i]]),
            "valid_history": bool(vec[0]),
        }
    return verdicts
