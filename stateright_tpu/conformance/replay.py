"""Vmapped trace-conformance replay over the packed model.

The swarm's walk kernel (``checker/tpu_simulation.walk_lane_step``)
samples its next action with ``jax.random.categorical``; conformance
replay is the same lane loop with the sampler replaced by the *trace* —
each lane replays one uploaded action sequence through
``model.packed_step`` and reports whether the recorded execution is a
behaviour of the model:

- a step whose ``valid`` bit is False is a **divergence**: the recorded
  action's guard does not hold where the trace claims it fired (the
  host model would never have enumerated it there). The verdict is the
  first divergence index plus the offending action id, per lane — the
  exact "your deployment did something the model forbids, here" answer.
- lanes are traces; a bucket of same-shape traces (same model config,
  same padded length T) is ONE jitted ``vmap(lax.scan)`` dispatch, so
  a resident service replays thousands of traces per dispatch at wave
  throughput.

``replay_host`` is the parity oracle: the same loop as concrete host
python, diffed bit-for-bit (divergence index AND offending action) by
the parity suite and the checker's gate. Padding is honest: action
slots past a trace's real length are -1 and never step, so a short
trace in a long bucket cannot pick up phantom divergences.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _gather_inits(model, init_indices: Sequence[int]):
    """Stacks the requested rows of ``packed_init_states()`` into a
    lane-batched pytree (host-side; init indices were validated at
    ingestion)."""
    import jax

    idx = np.asarray(list(init_indices), np.int32)
    seeds = model.packed_init_states()
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[idx], seeds)


def validate_trace(rec: dict, model) -> Optional[str]:
    """Model-aware ingestion check for one decoded trace record: action
    ids must be dense ids of this model, the init index must exist.
    Returns a refusal reason or None. (Wire decode cannot do this — it
    has no model; the checker calls it once the factory resolved.)"""
    A = model.packed_action_count()
    import jax

    leaves = jax.tree_util.tree_leaves(model.packed_init_states())
    n_init = int(leaves[0].shape[0]) if leaves else 0
    if rec["init"] >= n_init:
        return (
            f"init index {rec['init']} out of range "
            f"(model has {n_init} initial states)"
        )
    bad = [a for a in rec["actions"] if a >= A]
    if bad:
        return (
            f"action id {bad[0]} out of range (model has {A} actions)"
        )
    return None


_KERNELS: Dict[tuple, object] = {}
_KERNELS_LOCK = threading.Lock()


def replay_kernel(model, namespace: str, T: int, L: int):
    """The jitted batch replayer for one (model config, padded length,
    lane count) shape: ``fn(inits pytree[L, ...], actions (L, T) i32)
    -> dict of (L,) arrays``. Cached process-globally keyed on the zoo
    namespace (two jobs submitting the same config share the
    executable — the conformance analog of the shared AOT wave cache).
    """
    key = (namespace, T, L)
    with _KERNELS_LOCK:
        fn = _KERNELS.get(key)
        if fn is not None:
            return fn
    import jax
    import jax.numpy as jnp

    A = model.packed_action_count()

    def lane(init_state, actions):
        def step(carry, a):
            state, diverged, div_idx, offending, steps, i = carry
            active = (a >= 0) & ~diverged
            nxt, valid = model.packed_step(
                state, jnp.clip(a, 0, A - 1)
            )
            advance = active & valid
            state = jax.tree_util.tree_map(
                lambda n, c: jnp.where(advance, n, c), nxt, state
            )
            diverge_now = active & ~valid
            div_idx = jnp.where(diverge_now, i, div_idx)
            offending = jnp.where(diverge_now, a, offending)
            diverged = diverged | diverge_now
            steps = steps + advance.astype(jnp.int32)
            return (state, diverged, div_idx, offending, steps, i + 1), None

        carry = (
            init_state,
            jnp.bool_(False),
            jnp.int32(-1),
            jnp.int32(-1),
            jnp.int32(0),
            jnp.int32(0),
        )
        (state, diverged, div_idx, offending, steps, _), _ = jax.lax.scan(
            step, carry, actions
        )
        hi, lo = model.packed_fingerprint(state)
        return {
            "diverged": diverged,
            "divergence_index": div_idx,
            "offending_action": offending,
            "steps": steps,
            "fp_hi": hi,
            "fp_lo": lo,
        }

    fn = jax.jit(jax.vmap(lane))
    with _KERNELS_LOCK:
        _KERNELS[key] = fn
    return fn


def clear_replay_kernels() -> None:
    """Test hook: drop the process-global kernel cache."""
    with _KERNELS_LOCK:
        _KERNELS.clear()


def warm_replay(model, namespace: str, T: int, L: int):
    """Compiles the replay executable for one shape by executing it
    once on an inert batch (all-padding lanes) — the warm pool's
    conformance registration. Returns the cached kernel."""
    fn = replay_kernel(model, namespace, T, L)
    actions = np.full((L, T), -1, np.int32)
    inits = _gather_inits(model, [0] * L)
    out = fn(inits, actions)
    np.asarray(out["diverged"])  # block until the compile+run lands
    return fn


def pad_actions(records: Sequence[dict], T: int, L: int) -> np.ndarray:
    """(L, T) int32 action grid: row per record padded with -1 (inert),
    then whole inert rows up to the fixed lane count L — short batches
    reuse the bucket's compiled executable instead of retracing."""
    out = np.full((L, T), -1, np.int32)
    for i, rec in enumerate(records):
        acts = rec["actions"]
        out[i, : len(acts)] = acts
    return out


def replay_batch(
    records: Sequence[dict], model, namespace: str, T: int,
    lanes: Optional[int] = None,
) -> List[dict]:
    """Replays one shape bucket of decoded traces in one vmapped
    dispatch -> one verdict dict per record, in order: ``{"id", "kind":
    "trace", "conforms", "divergence_index", "offending_action",
    "steps", "fingerprint"}``."""
    if not records:
        return []
    L = lanes or len(records)
    if len(records) > L:
        raise ValueError(
            f"{len(records)} traces exceed the {L}-lane batch"
        )
    actions = pad_actions(records, T, L)
    inits = _gather_inits(
        model, [r["init"] for r in records] + [0] * (L - len(records))
    )
    out = replay_kernel(model, namespace, T, L)(inits, actions)
    out = {k: np.asarray(v) for k, v in out.items()}
    verdicts = []
    for i, rec in enumerate(records):
        diverged = bool(out["diverged"][i])
        verdicts.append({
            "id": rec["id"],
            "kind": "trace",
            "conforms": not diverged,
            "divergence_index": (
                int(out["divergence_index"][i]) if diverged else None
            ),
            "offending_action": (
                int(out["offending_action"][i]) if diverged else None
            ),
            "steps": int(out["steps"][i]),
            "fingerprint": (
                int(out["fp_hi"][i]) << 32 | int(out["fp_lo"][i])
            ),
        })
    return verdicts


def replay_host(rec: dict, model) -> dict:
    """The concrete host oracle: the same replay as plain python over
    ``packed_step`` on unbatched arrays. Device verdicts are gated on
    matching this bit-for-bit (index and offending action included)."""
    import jax
    import jax.numpy as jnp

    state = jax.tree_util.tree_map(
        lambda x: x[rec["init"]], model.packed_init_states()
    )
    steps = 0
    for i, a in enumerate(rec["actions"]):
        nxt, valid = model.packed_step(state, jnp.int32(a))
        if not bool(valid):
            hi, lo = model.packed_fingerprint(state)
            return {
                "id": rec["id"], "kind": "trace", "conforms": False,
                "divergence_index": i, "offending_action": a,
                "steps": steps,
                "fingerprint": int(hi) << 32 | int(lo),
            }
        state = nxt
        steps += 1
    hi, lo = model.packed_fingerprint(state)
    return {
        "id": rec["id"], "kind": "trace", "conforms": True,
        "divergence_index": None, "offending_action": None,
        "steps": steps, "fingerprint": int(hi) << 32 | int(lo),
    }
