"""Labeled conformance corpora: generated histories and traces with
known ground truth.

Bench legs and parity tests need uploads whose verdicts are *knowable*:

- **histories** come out of an actual concurrent execution simulator —
  ops commit atomically at their return event, so every ``clean``
  history is linearizable by construction (the witness order is the
  commit order); ``random`` histories draw returns uniformly instead
  (a mix of consistent and violating, labeled only by the host
  oracle); ``invalid`` histories take a clean skeleton and inject the
  two client-bug edges the host testers latch on (double invoke,
  orphan return). Any history may leave ops in flight.
- **traces** are random walks over the packed model (uniform over the
  *valid* actions at each step — by construction a behaviour of the
  model); ``mutate_trace`` replants one recorded action with an action
  whose guard is provably false at that point, yielding a trace whose
  first divergence index is known exactly.

Labels ride the wire frames' free-form ``meta`` field (``expect`` /
``divergence_index``), which the parity suite reads back. Everything is
seeded — a corpus is reproducible from its generator seed.
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence, Tuple

from ..semantics.register import Register
from ..semantics.vec import VecSpec

_ALPHABET = "abcdef"


def _random_op(rng: random.Random, spec: str) -> Tuple[str, Optional[str]]:
    if spec == "register":
        if rng.random() < 0.5:
            return "Write", rng.choice(_ALPHABET)
        return "Read", None
    roll = rng.random()
    if roll < 0.45:
        return "Push", rng.choice(_ALPHABET)
    if roll < 0.85:
        return "Pop", None
    return "Len", None


def _commit_ret(spec_obj, tag: str, value):
    """Executes one op atomically on the live spec object -> the decoded
    return payload (the wire codec's normalized form)."""
    if tag == "Write":
        spec_obj.invoke(("Write", value))
        return None
    if tag == "Read":
        return spec_obj.invoke(("Read",))[1]
    if tag == "Push":
        spec_obj.invoke(("Push", value))
        return None
    if tag == "Pop":
        ret = spec_obj.invoke(("Pop",))
        return ("none",) if ret[1] is None else ("some", ret[1][1])
    return spec_obj.invoke(("Len",))[1]


def _random_ret(rng: random.Random, tag: str):
    if tag in ("Write", "Push"):
        return None
    if tag == "Read":
        return rng.choice(_ALPHABET)
    if tag == "Pop":
        if rng.random() < 0.4:
            return ("none",)
        return ("some", rng.choice(_ALPHABET))
    return rng.randrange(0, 5)


def random_history(
    rng: random.Random,
    spec: str = "register",
    semantics: str = "linearizability",
    threads: int = 2,
    ops_per_thread: int = 2,
    mode: str = "clean",
    default: str = "a",
    inflight_prob: float = 0.25,
    rec_id: str = "h0",
) -> dict:
    """One decoded history record (``wire.decode_lines`` output shape)
    with a ``meta.expect`` label: ``clean`` -> consistent by
    construction; ``random`` -> unlabeled (oracle decides); ``invalid``
    -> invalid history (both testers report inconsistent)."""
    assert mode in ("clean", "random", "invalid")
    spec_obj = Register(default) if spec == "register" else VecSpec()
    remaining = {t: ops_per_thread for t in range(threads)}
    inflight = {}  # tid -> (tag, value)
    events = []
    while any(remaining.values()) or inflight:
        can_invoke = [
            t for t, n in remaining.items() if n > 0 and t not in inflight
        ]
        can_return = list(inflight)
        if can_invoke and (not can_return or rng.random() < 0.5):
            t = rng.choice(can_invoke)
            tag, value = _random_op(rng, spec)
            inflight[t] = (tag, value)
            remaining[t] -= 1
            events.append(("invoke", t, tag, value))
        else:
            t = rng.choice(can_return)
            tag, value = inflight.pop(t)
            # Leave a tail op in flight sometimes (the edge the packed
            # codecs must model: generated returns are unconstrained).
            if (
                not remaining[t] and rng.random() < inflight_prob
                and mode != "invalid"
            ):
                inflight[t] = (tag, value)
                del inflight[t]
                continue  # drop the return: op stays in flight forever
            if mode == "random":
                ret = _random_ret(rng, tag)
            else:
                ret = _commit_ret(spec_obj, tag, value)
            events.append(("return", t, tag, ret))
    if mode == "invalid":
        # Inject one of the two latching client bugs at a random point.
        if rng.random() < 0.5 and any(
            e[0] == "invoke" for e in events
        ):
            # Double invoke: re-invoke a thread right after its invoke.
            idx = rng.choice(
                [i for i, e in enumerate(events) if e[0] == "invoke"]
            )
            t = events[idx][1]
            tag, value = _random_op(rng, spec)
            events.insert(idx + 1, ("invoke", t, tag, value))
        else:
            # Orphan return: a return for a thread with nothing in
            # flight, at the very start.
            t = rng.randrange(threads)
            events.insert(0, ("return", t, None, None))
    meta = {"expect": "consistent" if mode == "clean" else mode}
    return {
        "kind": "history",
        "id": rec_id,
        "semantics": semantics,
        "spec": spec,
        "default": default if spec == "register" else None,
        "events": events,
        "meta": meta,
    }


# -- traces -----------------------------------------------------------------


def _valid_actions(model, state) -> List[int]:
    import jax
    import numpy as np

    _cand, valid = model.packed_expand(state)
    return [int(a) for a in np.nonzero(np.asarray(valid))[0]]


def random_walk_trace(
    model, rng: random.Random, steps: int, init: int = 0,
    rec_id: str = "t0", model_name: str = "", model_args: Optional[dict] = None,
) -> dict:
    """One decoded trace record: a seeded uniform random walk over the
    model's valid actions — a behaviour of the model by construction
    (``meta.expect = "clean"``). Stops early at terminal states."""
    import jax

    import jax.numpy as jnp

    state = jax.tree_util.tree_map(
        lambda x: x[init], model.packed_init_states()
    )
    actions: List[int] = []
    for _ in range(steps):
        valid = _valid_actions(model, state)
        if not valid:
            break
        a = rng.choice(valid)
        actions.append(a)
        state, _ok = model.packed_step(state, jnp.int32(a))
    if not actions:
        raise ValueError("initial state is terminal; no trace to record")
    return {
        "kind": "trace",
        "id": rec_id,
        "model": model_name,
        "model_args": dict(model_args or {}),
        "init": init,
        "actions": actions,
        "meta": {"expect": "clean"},
    }


def mutate_trace(model, rng: random.Random, rec: dict) -> Optional[dict]:
    """A divergent twin of one clean trace: one recorded action is
    replaced by an action whose guard is false at that point, so the
    first divergence index is known exactly (``meta.divergence_index``).
    Returns None when every action is enabled everywhere along the
    trace (no mutation site exists)."""
    import jax

    import jax.numpy as jnp

    A = model.packed_action_count()
    state = jax.tree_util.tree_map(
        lambda x: x[rec["init"]], model.packed_init_states()
    )
    sites: List[Tuple[int, List[int]]] = []
    for i, a in enumerate(rec["actions"]):
        valid = set(_valid_actions(model, state))
        invalid = [x for x in range(A) if x not in valid]
        if invalid:
            sites.append((i, invalid))
        state, _ok = model.packed_step(state, jnp.int32(a))
    if not sites:
        return None
    k, invalid = sites[rng.randrange(len(sites))]
    actions = list(rec["actions"])
    offending = rng.choice(invalid)
    actions[k] = offending
    return {
        **rec,
        "id": rec["id"] + "-div",
        "actions": actions,
        "meta": {
            "expect": "divergent",
            "divergence_index": k,
            "offending_action": offending,
        },
    }


def generate_corpus(
    seed: int,
    model_specs: Sequence[Tuple[str, dict, object]] = (),
    traces_per_model: int = 4,
    mutated_per_model: int = 2,
    trace_steps: int = 12,
    histories: int = 12,
    history_shapes: Sequence[Tuple[str, str, int, int]] = (
        ("register", "linearizability", 2, 2),
        ("register", "sequential", 2, 2),
        ("vec", "linearizability", 2, 2),
    ),
) -> List[dict]:
    """A labeled mixed corpus: clean + mutated traces per model config,
    clean/random/invalid histories per shape. ``model_specs`` is
    ``(zoo_name, args, model_instance)`` triples. Deterministic in
    ``seed``."""
    rng = random.Random(seed)
    out: List[dict] = []
    for name, args, model in model_specs:
        clean = []
        for i in range(traces_per_model):
            rec = random_walk_trace(
                model, rng, trace_steps, rec_id=f"{name}-t{i}",
                model_name=name, model_args=args,
            )
            clean.append(rec)
            out.append(rec)
        made = 0
        for rec in clean:
            if made >= mutated_per_model:
                break
            mut = mutate_trace(model, rng, rec)
            if mut is not None:
                out.append(mut)
                made += 1
    modes = ["clean", "random", "invalid"]
    for i in range(histories):
        spec, semantics, C, O = history_shapes[i % len(history_shapes)]
        mode = modes[i % len(modes)]
        out.append(random_history(
            rng, spec=spec, semantics=semantics, threads=C,
            ops_per_thread=O, mode=mode,
            rec_id=f"{spec[:3]}-{semantics[:3]}-h{i}",
        ))
    return out
