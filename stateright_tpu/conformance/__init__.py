"""Conformance plane: device-batched trace replay + consistency
auditing as a service traffic class.

- ``wire`` — the versioned JSONL ingestion format (honest refusals,
  shape bucketing).
- ``replay`` — vmapped trace-conformance replay over the packed model
  (first-divergence verdicts) + the host oracle.
- ``audit`` — batched device linearizability / sequential-consistency
  auditing (register DP, vec lane grid) + the host-tester oracle.
- ``checker`` — the ``Checker``-shaped worker the service spawns for
  ``mode="conformance"`` jobs.
- ``corpus`` — labeled corpus generators (clean/mutated traces,
  clean/random/invalid histories) for benches and parity suites.
"""

from .audit import (
    MAX_VEC_LANES,
    PackedVecHistory,
    audit_batch,
    audit_kernel,
    clear_audit_kernels,
    host_is_consistent,
    pack_history,
)
from .checker import ConformanceChecker, bucket_label
from .corpus import (
    generate_corpus,
    mutate_trace,
    random_history,
    random_walk_trace,
)
from .replay import (
    clear_replay_kernels,
    replay_batch,
    replay_host,
    replay_kernel,
    validate_trace,
)
from .wire import (
    WIRE_VERSION,
    WireRefusal,
    bucket_key,
    bucket_records,
    decode_frame,
    decode_lines,
    encode_record,
    history_shape,
)

__all__ = [
    "MAX_VEC_LANES",
    "PackedVecHistory",
    "WIRE_VERSION",
    "WireRefusal",
    "ConformanceChecker",
    "audit_batch",
    "audit_kernel",
    "bucket_key",
    "bucket_label",
    "bucket_records",
    "clear_audit_kernels",
    "clear_replay_kernels",
    "decode_frame",
    "decode_lines",
    "encode_record",
    "generate_corpus",
    "history_shape",
    "host_is_consistent",
    "mutate_trace",
    "pack_history",
    "random_history",
    "random_walk_trace",
    "replay_batch",
    "replay_host",
    "replay_kernel",
    "validate_trace",
]
