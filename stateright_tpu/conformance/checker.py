"""The conformance checker: uploaded recordings as a service traffic
class.

``ConformanceChecker`` wears the ``Checker`` interface so the service's
entire machinery — journal, retries, fault classes, preempt/resume, SLO
ledger, monitor/SSE — applies to conformance jobs without a parallel
code path. A "run" is the deterministic processing of one upload:

- records are shape-bucketed (``wire.bucket_records`` keys) and each
  bucket streams through the device in fixed ``batch_lanes`` chunks —
  one vmapped dispatch per chunk (``replay_batch`` for traces,
  ``audit_batch`` for histories). Short chunks pad to the fixed lane
  count so a resident service reuses the bucket's executable.
- every chunk crosses the ``conformance.batch`` fault seam. Verdicts
  are a pure function of the upload, so a journaled retry recovers
  bit-identically — the acceptance gate the fault tests pin.
- preemption suspends at a chunk boundary into a payload of finished
  verdicts; the resumed incarnation skips them (same verdicts — they
  ride the payload verbatim).
- the ``Checker`` counters are reinterpreted honestly: ``state_count``
  = replay steps + audited events, ``unique_state_count`` = records
  finalized, ``max_depth`` = longest trace. ``_discovery_names`` are
  the ids of non-conforming/violating records, so the service's
  time-to-first-violation probe works unchanged.

``parity=True`` arms the per-batch host gate: every device verdict is
recomputed with the host oracles (``replay_host`` /
``host_is_consistent``) and any mismatch kills the run — the seed
corpus rides through the tier-1 smoke with this on.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ..checker.base import Checker
from ..utils.faults import fault_point
from .audit import audit_batch, host_is_consistent
from .replay import replay_batch, replay_host, validate_trace
from .wire import bucket_key

_TRACE_VERDICT_KEYS = (
    "conforms", "divergence_index", "offending_action", "steps",
    "fingerprint",
)


class _NullModel:
    """The property surface of a run with no model: conformance verdicts
    are per-record, not per-property, so the base reporter/assertion
    machinery sees an empty property list (``assert_properties`` is
    overridden with the real per-record gate)."""

    def properties(self):
        return []

    def property(self, name):
        raise KeyError(name)


class _Preempted(Exception):
    """Internal worker unwind for a preempt request — not an error."""


def bucket_label(key: tuple) -> str:
    """Human-readable bucket key for reports and histograms."""
    if key[0] == "trace":
        _kind, model, args, T = key
        arg_s = ",".join(f"{k}={v}" for k, v in args)
        return f"trace:{model}({arg_s})[T={T}]"
    _kind, spec, semantics, C, O, default = key
    shape = f"C={C},O={O}"
    if default is not None:
        shape += f",default={default}"
    return f"history:{spec}/{semantics}[{shape}]"


class ConformanceChecker(Checker):
    supports_preempt = True
    supports_packing = False
    packing_reason = (
        "conformance batches are internally lane-packed (lanes = "
        "traces/histories); cross-tenant packing would break the "
        "per-upload verdict determinism the retry gate pins"
    )

    def __init__(
        self,
        records: Sequence[dict],
        zoo: Optional[dict] = None,
        *,
        run_id: Optional[str] = None,
        batch_lanes: int = 64,
        resume_from: Optional[dict] = None,
        parity: bool = False,
        tenant=None,
    ):
        self._records = list(records)
        if zoo is None:
            from ..service.zoo import default_zoo

            zoo = default_zoo()
        self._zoo = zoo
        self.run_id = run_id
        if run_id is not None:
            from ..telemetry import metrics_registry

            self._registry = metrics_registry(run_id)
        self._batch_lanes = max(1, int(batch_lanes))
        self._parity = bool(parity)
        self._tenant = tenant
        self._model_obj = _NullModel()
        self._lock = threading.Lock()
        # record index -> verdict dict; index keys (not ids) because
        # uploaded ids may collide.
        self._verdicts: Dict[int, dict] = {}
        self._counts = {"steps": 0, "events": 0, "max_depth": 0}
        self._trace_secs = 0.0
        self._traces_done = 0
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._preempt = threading.Event()
        if resume_from:
            self._verdicts.update(
                {int(k): v for k, v in resume_from["verdicts"].items()}
            )
            self._counts.update(resume_from.get("counts") or {})
            self._trace_secs = resume_from.get("trace_secs", 0.0)
            self._traces_done = resume_from.get("traces_done", 0)
        m = self.metrics()
        self._m_traces = m.counter("conformance.traces")
        self._m_histories = m.counter("conformance.histories")
        self._m_batches = m.counter("conformance.batches")
        self._m_divergences = m.counter("conformance.divergences")
        self._m_violations = m.counter("conformance.violations")
        self._m_refusals = m.counter("conformance.refusals")
        self._m_lanes = m.histogram("conformance.bucket_lanes")
        self._m_secs = m.histogram("conformance.batch_seconds")
        self._m_rate = m.gauge("conformance.traces_per_s")
        self._worker = threading.Thread(
            target=self._run, name="conformance-worker", daemon=True
        )
        self._handles: List[threading.Thread] = [self._worker]
        self._worker.start()

    # -- worker -------------------------------------------------------------

    def _run(self) -> None:
        try:
            self._process()
        except _Preempted:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced as worker_error
            self._error = e
        finally:
            if self._trace_secs > 0:
                self._m_rate.set(self._traces_done / self._trace_secs)
            self._done.set()

    def _maybe_preempt(self) -> None:
        if not self._preempt.is_set():
            return
        with self._lock:
            self._preempt_payload = {
                "verdicts": dict(self._verdicts),
                "counts": dict(self._counts),
                "trace_secs": self._trace_secs,
                "traces_done": self._traces_done,
            }
        raise _Preempted

    def _process(self) -> None:
        buckets: Dict[tuple, List[int]] = {}
        for i, rec in enumerate(self._records):
            buckets.setdefault(bucket_key(rec), []).append(i)
        self._bucket_sizes = {
            bucket_label(k): len(v) for k, v in buckets.items()
        }
        for key, indices in buckets.items():
            pending = [i for i in indices if i not in self._verdicts]
            if not pending:
                continue
            if key[0] == "trace":
                self._trace_bucket(key, pending)
            else:
                self._history_bucket(key, pending)
        self._maybe_preempt()

    def _finish(self, idx: int, verdict: dict, events: int = 0,
                steps: int = 0, depth: int = 0) -> None:
        with self._lock:
            self._verdicts[idx] = verdict
            self._counts["steps"] += steps
            self._counts["events"] += events
            self._counts["max_depth"] = max(
                self._counts["max_depth"], depth
            )
        if verdict.get("refused") is not None:
            self._m_refusals.inc()
        elif verdict["kind"] == "trace":
            if not verdict["conforms"]:
                self._m_divergences.inc()
        elif not verdict["consistent"]:
            self._m_violations.inc()

    def _refuse_bucket(self, pending: List[int], reason: str,
                       kind: str) -> None:
        for i in pending:
            self._finish(i, {
                "id": self._records[i]["id"], "kind": kind,
                "refused": reason,
            })

    def _trace_bucket(self, key: tuple, pending: List[int]) -> None:
        from ..service.zoo import aot_namespace

        _kind, model_name, _args_key, T = key
        args = self._records[pending[0]]["model_args"]
        factory = self._zoo.get(model_name)
        if factory is None:
            self._refuse_bucket(
                pending, f"unknown zoo model {model_name!r}", "trace"
            )
            return
        try:
            model = factory(**args)
        except Exception as e:  # noqa: BLE001 - bad args are a refusal
            self._refuse_bucket(
                pending,
                f"model {model_name!r} rejected args {args!r}: {e}",
                "trace",
            )
            return
        namespace = aot_namespace(model_name, args)
        runnable: List[int] = []
        for i in pending:
            reason = validate_trace(self._records[i], model)
            if reason is not None:
                self._finish(i, {
                    "id": self._records[i]["id"], "kind": "trace",
                    "refused": reason,
                })
            else:
                runnable.append(i)
        L = self._batch_lanes
        for lo in range(0, len(runnable), L):
            self._maybe_preempt()
            chunk = runnable[lo: lo + L]
            recs = [self._records[i] for i in chunk]
            fault_point("conformance.batch", tenant=self._tenant)
            t0 = time.perf_counter()
            verdicts = replay_batch(recs, model, namespace, T, lanes=L)
            dt = time.perf_counter() - t0
            self._m_batches.inc()
            self._m_lanes.observe(len(chunk))
            self._m_secs.observe(dt)
            self._m_traces.inc(len(chunk))
            self._trace_secs += dt
            self._traces_done += len(chunk)
            for i, rec, v in zip(chunk, recs, verdicts):
                if self._parity:
                    host = replay_host(rec, model)
                    if any(
                        v[k] != host[k] for k in _TRACE_VERDICT_KEYS
                    ):
                        raise RuntimeError(
                            f"conformance parity gate: device verdict "
                            f"{v!r} != host {host!r} for record "
                            f"{rec['id']!r}"
                        )
                self._finish(
                    i, v, steps=v["steps"], depth=len(rec["actions"])
                )

    def _history_bucket(self, key: tuple, pending: List[int]) -> None:
        L = self._batch_lanes
        for lo in range(0, len(pending), L):
            self._maybe_preempt()
            chunk = pending[lo: lo + L]
            recs = [self._records[i] for i in chunk]
            fault_point("conformance.batch", tenant=self._tenant)
            t0 = time.perf_counter()
            verdicts = audit_batch(recs, lanes=L)
            dt = time.perf_counter() - t0
            self._m_batches.inc()
            self._m_lanes.observe(len(chunk))
            self._m_secs.observe(dt)
            self._m_histories.inc(len(chunk))
            for i, rec, v in zip(chunk, recs, verdicts):
                if self._parity and v.get("refused") is None:
                    host = host_is_consistent(rec)
                    if v["consistent"] != host:
                        raise RuntimeError(
                            f"conformance parity gate: device "
                            f"consistent={v['consistent']} != host "
                            f"{host} for record {rec['id']!r}"
                        )
                self._finish(i, v, events=len(rec["events"]))

    # -- Checker surface ----------------------------------------------------

    def model(self):
        return self._model_obj

    def state_count(self) -> int:
        with self._lock:
            return self._counts["steps"] + self._counts["events"]

    def unique_state_count(self) -> int:
        with self._lock:
            return len(self._verdicts)

    def max_depth(self) -> int:
        with self._lock:
            return self._counts["max_depth"]

    def discoveries(self):
        return {}

    def _discovery_names(self) -> List[str]:
        with self._lock:
            return [
                v["id"] for v in self._verdicts.values()
                if self._failing(v)
            ]

    @staticmethod
    def _failing(v: dict) -> bool:
        if v.get("refused") is not None:
            return False
        if v["kind"] == "trace":
            return not v["conforms"]
        return not v["consistent"]

    def handles(self) -> List[threading.Thread]:
        out, self._handles = self._handles, []
        return out

    def is_done(self) -> bool:
        return self._done.is_set()

    def worker_error(self) -> Optional[BaseException]:
        return self._error

    def request_preempt(self) -> None:
        self._preempt.set()

    def assert_properties(self) -> None:
        failing = self._discovery_names()
        if failing:
            raise AssertionError(
                f"{len(failing)} record(s) failed conformance: "
                f"{sorted(failing)[:8]}"
            )

    def conformance_report(self) -> dict:
        """The verdict block the service attaches to the job result:
        one verdict per uploaded record, in upload order, plus batch
        accounting (records always sum to the upload)."""
        with self._lock:
            verdicts = [
                self._verdicts.get(i) for i in range(len(self._records))
            ]
        traces = sum(
            1 for v in verdicts
            if v and v["kind"] == "trace" and v.get("refused") is None
        )
        histories = sum(
            1 for v in verdicts
            if v and v["kind"] == "history" and v.get("refused") is None
        )
        refused = sum(
            1 for v in verdicts if v and v.get("refused") is not None
        )
        divergences = sum(
            1 for v in verdicts
            if v and v["kind"] == "trace" and v.get("refused") is None
            and not v["conforms"]
        )
        violations = sum(
            1 for v in verdicts
            if v and v["kind"] == "history" and v.get("refused") is None
            and not v["consistent"]
        )
        out = {
            "records": verdicts,
            "traces": traces,
            "histories": histories,
            "refusals": refused,
            "divergences": divergences,
            "violations": violations,
            "buckets": dict(getattr(self, "_bucket_sizes", {})),
        }
        if self._trace_secs > 0:
            out["traces_per_s"] = self._traces_done / self._trace_secs
        return out
