"""Multi-device scale-out: fingerprint-sharded checking over a device mesh.

The reference's parallelism is N worker threads around one concurrent map
(``/root/reference/src/job_market.rs``); this package is its TPU-native
replacement — ``jax.sharding.Mesh`` + ``shard_map`` with XLA collectives
doing the frontier/visited-set exchange over ICI/DCN (SURVEY §2.8).
"""

from .base_mesh import (
    AXIS,
    bootstrap_mesh,
    default_mesh,
    distributed_mesh,
    initialize_distributed,
)

__all__ = [
    "AXIS",
    "ShardedTpuBfsChecker",
    "bootstrap_mesh",
    "default_mesh",
    "distributed_mesh",
    "initialize_distributed",
]


def __getattr__(name):
    # Lazy: importing the checker builds jnp module constants, i.e. runs
    # a computation — which would poison multi-host processes that must
    # call ``bootstrap_mesh()`` (jax.distributed.initialize) as their
    # very first jax-touching act. Keeping this module light makes
    # ``from stateright_tpu.parallel import bootstrap_mesh`` safe to run
    # first in every controller process.
    if name == "ShardedTpuBfsChecker":
        from .sharded import ShardedTpuBfsChecker

        return ShardedTpuBfsChecker
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
