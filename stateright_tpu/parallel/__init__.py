"""Multi-device scale-out: fingerprint-sharded checking over a device mesh.

The reference's parallelism is N worker threads around one concurrent map
(``/root/reference/src/job_market.rs``); this package is its TPU-native
replacement — ``jax.sharding.Mesh`` + ``shard_map`` with XLA collectives
doing the frontier/visited-set exchange over ICI/DCN (SURVEY §2.8).
"""

from .base_mesh import AXIS, default_mesh
from .sharded import ShardedTpuBfsChecker

__all__ = ["AXIS", "ShardedTpuBfsChecker", "default_mesh"]
