"""Mesh construction helpers for the sharded checkers.

The canonical mesh has one axis, ``"fp"`` — devices own fingerprint ranges
of the visited set. On real hardware this spans the TPU slice (and hosts,
under ``jax.distributed``); in tests it is the virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh

AXIS = "fp"


def _pow2floor(n: int) -> int:
    return 1 << (n.bit_length() - 1)


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D ``"fp"`` mesh over the first ``n_devices`` devices.

    Defaults to the largest power-of-two prefix of ``jax.devices()``
    (collectives are fastest on power-of-two rings); any explicit count
    works — the hash owner function is a modulo.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = _pow2floor(len(devices))
    if n_devices > len(devices):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devices)} available"
        )
    return Mesh(np.array(devices[:n_devices]), (AXIS,))
