"""Mesh construction helpers for the sharded checkers.

The canonical mesh has one axis, ``"fp"`` — devices own fingerprint ranges
of the visited set. On real hardware this spans the TPU slice (and hosts,
under ``jax.distributed``); in tests it is the virtual 8-device CPU mesh.

Multi-host entry point: call :func:`bootstrap_mesh` once per process (on a
pod slice, or a multi-process CPU mesh in CI) — it initializes
``jax.distributed`` idempotently and returns the global ``"fp"`` mesh over
every device in the job. Single-process callers can keep using
:func:`default_mesh` unchanged.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh

AXIS = "fp"

# Set by initialize_distributed so repeat calls (idempotent bootstrap,
# tests that re-enter) don't re-run jax.distributed.initialize, which
# raises once a client exists.
_DISTRIBUTED_STATE = {"initialized": False}


def _pow2floor(n: int) -> int:
    return 1 << (n.bit_length() - 1)


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D ``"fp"`` mesh over the first ``n_devices`` devices.

    Defaults to the largest power-of-two prefix of ``jax.devices()``
    (collectives are fastest on power-of-two rings); any explicit count
    works — the hash owner function is a modulo.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = _pow2floor(len(devices))
    if n_devices > len(devices):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devices)} available"
        )
    return Mesh(np.array(devices[:n_devices]), (AXIS,))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> bool:
    """Idempotent ``jax.distributed.initialize`` for multi-host runs.

    On TPU pods every argument auto-detects from the environment, so a
    bare call does the right thing; multi-process CPU meshes (the CI leg)
    pass coordinator/count/id explicitly. Returns ``True`` if this call
    performed the initialization, ``False`` if a client already existed
    (ours or anyone else's) — either way the process is usable afterwards.

    Must run before any other jax API touches the backend; jax itself
    enforces that, we just surface the error unchanged.
    """
    if _DISTRIBUTED_STATE["initialized"]:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except RuntimeError as e:
        # Already initialized elsewhere in this process: fine, adopt it.
        if "already" in str(e).lower():
            _DISTRIBUTED_STATE["initialized"] = True
            return False
        raise
    _DISTRIBUTED_STATE["initialized"] = True
    return True


def distributed_mesh() -> Mesh:
    """The global 1-D ``"fp"`` mesh over every device in the distributed
    job (all processes), in ``jax.devices()`` order — the mesh the
    sharded checker runs on after :func:`initialize_distributed`.

    Unlike :func:`default_mesh` this never truncates to a power of two:
    in a multi-process job every process must construct the IDENTICAL
    mesh, and every device must belong to it (shard_map requires the
    mesh to cover all addressable devices per process).
    """
    return Mesh(np.array(jax.devices()), (AXIS,))


def bootstrap_mesh(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> Mesh:
    """One-call multi-host entry point: initialize ``jax.distributed``
    (idempotently) and return the global ``"fp"`` mesh.

    Convention for explicit (non-auto-detected) runs — e.g. the CI CPU
    mesh — mirrors jax's own env fallbacks: arguments not passed are read
    from ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` when set, else left to jax's auto-detection.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    initialize_distributed(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    if jax.process_count() > 1 and jax.default_backend() == "cpu":
        # Gloo (the CPU backend's cross-process collectives) matches
        # sends to receives by issue order, not tags. Async dispatch
        # lets a dispatched executable's tail collectives overlap the
        # next call's — two processes can then hit the wire in
        # different orders and abort the job (gloo EnforceNotMet, size
        # mismatch). Serial dispatch pins the wire order to program
        # order. CPU-mesh stand-in only: TPU runtimes order their own
        # collectives.
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    return distributed_mesh()
