"""Multi-device BFS: fingerprint-sharded visited set + all-to-all key routing.

This is the scale-out design SURVEY §2.8 calls for: where the reference
shares one concurrent ``DashMap`` between N worker threads
(``/root/reference/src/checker/bfs.rs:28-29``, ``src/job_market.rs``), here
every device in a ``jax.sharding.Mesh`` owns

- a *shard of the visited hash set*, keyed by fingerprint range
  (``owner = hi mod n_shards``), and
- a *slice of the frontier*, which is purely data-parallel (any state may
  live on any device — only the visited set is fingerprint-addressed).

One wave, inside ``shard_map`` over mesh axis ``"fp"``:

1. each device expands its local frontier slice (F_loc × A grid) and
   fingerprints the candidates — pure local compute, MXU/VPU friendly;
2. candidate *keys* (8 bytes each — never the packed states) are bucketed
   by owner shard and exchanged with ``lax.all_to_all`` over ICI;
3. each owner sort-dedups the keys it received, claim-inserts them into its
   hash-set shard, and returns per-key fresh flags by the reverse
   ``all_to_all``;
4. senders compact their fresh candidates into the next local frontier
   slice — new states never move off the device that generated them.

The host loop only moves compacted *new-state* batches through a queue
(the host↔device frontier scheduler replacing the reference's
``JobBroker``) and ingests (child fp, parent fp) pairs for TLC-style path
reconstruction, identical to the single-device ``TpuBfsChecker``.

Multi-host: the same program runs under ``jax.distributed`` — the mesh
spans hosts, all-to-all rides ICI within a slice and DCN across slices,
every controller executes this same host loop in lockstep (host pulls
allgather; checkpoints written by process 0). Exercised end-to-end on a
2-process mesh in ``tests/test_multihost.py``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6: top-level export, replication check named check_vma
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental module, check_rep instead
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_compat(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.batch import BatchableModel
from ..core.model import Expectation
from ..core.path import Path
from ..native import make_fingerprint_store
from ..ops import comm_sieve
from ..ops.fingerprint import fingerprint_state, fp64_pairs, fp_to_int
from ..ops.hashset import MAX_PROBES, hashset_insert
from ..ops.ring import ring_export, ring_push, ring_rows, ring_take
from ..telemetry import (
    CommsInstruments,
    WaveInstruments,
    device_step_annotation,
    get_tracer,
    metrics_registry,
)
from .base_mesh import default_mesh
from ..checker.base import Checker
from ..checker.pipeline import HostPipeline
from ..utils.faults import fault_point
from ..checker.tpu import (
    _AUTO_BUCKET_MIN_F,
    _DEFAULT_BUCKET_STEPS,
    _make_key_fn,
    atomic_pickle,
    bucket_for,
    bucket_ladder_widths,
    checkpoint_header,
    sym_key_scheme,
    validate_checkpoint_header,
)

_DEPTH_INF = (1 << 31) - 1
_U32_MAX = np.uint32(0xFFFFFFFF)
_MAX_LOAD = 0.5


def _pow2ceil(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _sort_dedup(hi, lo, active):
    """Sorts (hi, lo) keys, returns (shi, slo, sidx, unique_mask).

    Inactive lanes sort to the end (key = U32_MAX pair) and are excluded
    from ``unique_mask``.
    """
    m = hi.shape[0]
    shi = jnp.where(active, hi, _U32_MAX)
    slo = jnp.where(active, lo, _U32_MAX)
    shi, slo, sidx = jax.lax.sort(
        (shi, slo, jnp.arange(m, dtype=jnp.int32)), num_keys=2
    )
    uniq = jnp.concatenate(
        [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
    )
    return shi, slo, sidx, active[sidx] & uniq


class ShardedTpuBfsChecker(Checker):
    """BFS over a device mesh; requires a ``BatchableModel``.

    ``frontier_per_device`` is the per-device frontier slice width (the
    global chunk is ``n_devices ×`` that); ``table_capacity_per_device``
    is each shard's initial hash-set size (grows by doubling + local
    rehash — keys never change owner, so rehash needs no communication).
    ``bucket_ladder`` is the occupancy-adaptive chunk-dispatch depth
    (power-of-two rungs below ``F_loc``; None auto-selects 4 when
    ``F_loc >= 512``, else fixed width; 0 forces fixed width); the
    wave-at-a-time path shrinks global chunks to ``n × bucket``.
    """

    def __init__(
        self,
        options,
        mesh: Optional[Mesh] = None,
        frontier_per_device: int = 1 << 10,
        table_capacity_per_device: int = 1 << 15,
        checkpoint_path=None,
        checkpoint_every_chunks=32,
        checkpoint_min_interval_s=0.0,
        resume_from=None,
        max_drain_waves=100_000,
        drain_log_factor=8,
        pool_factor=16,
        bucket_ladder=None,
        hbm_budget_mib=None,
        host_budget_mib=None,
        spill_dir=None,
        attribution=False,
        coverage=False,
        run_id=None,
        async_pipeline=False,
        liveness=None,
        wave_kernel="staged",
        aot_store=None,
        sieve=None,
        sieve_slots_per_device=None,
        sieve_bloom_bits=None,
        fleet=True,
    ):
        model = options.model
        if not isinstance(model, BatchableModel):
            raise TypeError(
                f"spawn_sharded_tpu_bfs requires a BatchableModel; "
                f"{type(model).__name__} does not implement the packed protocol"
            )
        # Honest capability surfacing (the single-device checker's
        # packing_reason pattern): there is no sharded fused path — the
        # Pallas megakernel fuses ONE device's wave into a single kernel
        # and cannot express the cross-shard all_to_all key exchange —
        # so asking for it refuses with the reason instead of silently
        # dispatching the staged wave under a label that lies.
        if wave_kernel not in ("staged", "fused"):
            raise ValueError(
                f"wave_kernel must be 'staged' or 'fused', got {wave_kernel!r}"
            )
        self._wave_kernel = "staged"
        self.wave_kernel_reason = (
            "wave_kernel='fused' has no sharded path: the fused Pallas "
            "megakernel runs one device's wave as a single kernel and "
            "cannot express the cross-shard all_to_all key exchange; "
            "use the single-device checker for the fused engine, or "
            "wave_kernel='staged' here"
            if wave_kernel == "fused"
            else None
        )
        if wave_kernel == "fused":
            raise ValueError(self.wave_kernel_reason)
        # Run identity (checking-as-a-service): own metrics registry +
        # run-stamped trace spans, mirroring TpuBfsChecker.
        self.run_id = run_id
        self._registry = metrics_registry(run_id) if run_id else None
        self._tracer = get_tracer(run_id)
        self._mesh = mesh if mesh is not None else default_mesh()
        n = self._mesh.devices.size
        self._n = n
        self._model = model
        self._properties = model.properties()
        self._conditions = model.packed_conditions()
        if len(self._conditions) != len(self._properties):
            raise ValueError(
                "packed_conditions() must align 1:1 with properties(): "
                f"{len(self._conditions)} != {len(self._properties)}"
            )
        eventually = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if len(eventually) > 32:
            raise ValueError("at most 32 eventually properties supported")
        self._ebit: Dict[int, int] = {pi: b for b, pi in enumerate(eventually)}
        self._ebits0 = sum(1 << b for b in self._ebit.values())
        self._A = model.packed_action_count()
        self._F_loc = _pow2ceil(frontier_per_device)
        self._G = n * self._F_loc  # global frontier chunk width
        # Occupancy-adaptive chunk dispatch (wave-at-a-time path): global
        # chunks shrink to ``n × bucket`` where bucket is the smallest
        # per-device ladder rung holding the pending rows — the host pool
        # count is exact, so no transfer is needed to pick it. The deep
        # drain keeps fixed F_loc waves (its ring pops already compact
        # live lanes to a per-device dense prefix).
        if bucket_ladder is None:
            bucket_ladder = (
                _DEFAULT_BUCKET_STEPS
                if self._F_loc >= _AUTO_BUCKET_MIN_F
                else 0
            )
        if bucket_ladder < 0:
            raise ValueError(
                f"bucket_ladder must be >= 0, got {bucket_ladder}"
            )
        self._buckets = bucket_ladder_widths(self._F_loc, bucket_ladder)
        # Probing masks with (capacity - 1): non-pow2 would address only a
        # subset of rows.
        self._cap_loc = _pow2ceil(table_capacity_per_device)
        # Compression-and-sieve routing (README "Pod-scale sharding"):
        # sieve=None resolves OFF — the rung-ladder exchange traces one
        # branch per rung, a compile cost the many tiny sharded runs in
        # the test tier cannot afford; dedicated tests and the multichip
        # bench opt in explicitly. Results are bit-identical either way:
        # the receipt cache only drops lanes whose key it re-checked in
        # full, i.e. lanes the owner would have answered fresh=False.
        self._sieve = bool(sieve) if sieve is not None else False
        if sieve_slots_per_device is None:
            sieve_slots_per_device = min(1 << 16, self._cap_loc)
        self._sieve_slots = _pow2ceil(max(8, sieve_slots_per_device))
        if sieve_bloom_bits is None:
            # Sized for the resident population one shard can hold under
            # the load cap (the filter is flushed whenever that
            # population evicts), clamped to 1 MiB of bit-bytes.
            sieve_bloom_bits = comm_sieve.bloom_bits_for(
                min(int(_MAX_LOAD * self._cap_loc), 1 << 20)
            )
        if sieve_bloom_bits & (sieve_bloom_bits - 1):
            raise ValueError(
                f"sieve_bloom_bits must be a power of two, got "
                f"{sieve_bloom_bits}"
            )
        self._sieve_bits = sieve_bloom_bits
        self._sieve_dev = None
        self._last_comms = None
        self._last_comms_per = None
        # Fleet skew forensics (telemetry/fleet.py): per-shard per-wave
        # rows ride the existing out_specs=P("fp") pulls (five extra
        # int32 scalars per shard per wave) and host tier walls are
        # attributed per shard. Opt-out (`fleet=False`) — the fold is
        # host-side numpy over n-length vectors and never feeds back
        # into the search, so bit-identity holds either way.
        self._fleet_on = bool(fleet)
        self._fi = None
        self._fleet_lock = threading.Lock()
        self._fleet_probe_s = [0.0] * n
        self._fleet_evict_s = [0.0] * n
        self._fleet_evict_bytes = [0] * n
        self._visitor = options._visitor
        self._target_state_count: Optional[int] = options._target_state_count
        self._depth_cap = options._target_max_depth or _DEPTH_INF
        self._setup_lasso(options)

        self._checkpoint_path = checkpoint_path
        # Counts dequeued global chunks; the time floor keeps wide frontiers
        # from checkpointing (full parent-map export + pickle) back to back.
        self._checkpoint_every = max(1, checkpoint_every_chunks)
        self._checkpoint_min_interval = checkpoint_min_interval_s
        self._resume_from = resume_from
        # Deep drain (device frontier rings; see _deep_drain_local). As in
        # TpuBfsChecker: 1 disables, and durability caps waves-per-drain.
        self._max_drain_waves = max(1, max_drain_waves)
        if checkpoint_path is not None:
            self._max_drain_waves = min(
                self._max_drain_waves, max(2, checkpoint_every_chunks)
            )
        self._Ll = max(
            max(1, drain_log_factor) * self._F_loc, self._F_loc * self._A
        )
        self._PCl = _pow2ceil(
            max(max(1, pool_factor) * self._F_loc, self._F_loc * self._A)
        )

        # Out-of-core tiering (stateright_tpu.storage): ``hbm_budget_mib``
        # hard-caps each shard's table; growth past the cap drains every
        # shard to its own host tier (fps are mesh-partitioned by
        # ``hi % n``, so runs stay shard-local), and harvested fresh rows
        # batch-probe the tiers at the wave's host exit. Probes take the
        # union over all stores (Bloom filters make non-owner probes O(1)
        # rejects), which keeps elastic restores — where ownership
        # re-routes — correct for free. ``host_budget_mib`` divides
        # evenly across the shards' stores.
        from ..storage import (
            StorageInstruments,
            TieredVisitedStore,
            max_table_rows_for_budget,
            validate_budget_knobs,
        )

        validate_budget_knobs(hbm_budget_mib, host_budget_mib, spill_dir)
        self._tiers = []
        self._si = None
        self._max_cap_loc = None
        if hbm_budget_mib is not None:
            max_cap = max_table_rows_for_budget(hbm_budget_mib)
            # A freshly-evicted shard must absorb one wave of received
            # keys under the load cap. Keys are uniform over shards
            # (fingerprints), so the floor is the balanced share
            # (F_loc×A) with 4x skew slack — the true worst case (every
            # key routing to one shard) is astronomically unlikely and
            # is caught by the eviction-retry guard in the wave loop
            # instead of pricing every budget for it.
            worst = 4 * self._F_loc * self._A
            min_cap = _pow2ceil(int(worst / _MAX_LOAD) + 1)
            if max_cap < min_cap:
                raise ValueError(
                    f"hbm_budget_mib={hbm_budget_mib} allows a per-shard "
                    f"table of {max_cap} rows, but one wave "
                    f"({worst} routed keys at 4x skew) needs at least "
                    f"{min_cap}; raise the budget or shrink "
                    "frontier_per_device"
                )
            self._max_cap_loc = max_cap
            self._cap_loc = min(self._cap_loc, max_cap)
            self._si = StorageInstruments(
                "sharded_bfs", registry=self._registry
            )
            self._tiers = [
                TieredVisitedStore(
                    host_budget_mib=(
                        host_budget_mib / n
                        if host_budget_mib is not None
                        else None
                    ),
                    spill_dir=spill_dir,
                    instruments=self._si,
                    shard=d,
                    tracer=self._tracer,
                    # Fault-attribution tag (utils/faults.py): lets a
                    # chaos spec stall/kill exactly one shard's host
                    # tier — the injected-straggler seam the fleet skew
                    # forensics are tested against (tests/test_fleet.py).
                    owner=f"shard-{d}",
                )
                for d in range(n)
            ]
            # Out-of-core needs the per-wave host probe, which only the
            # wave-at-a-time path performs.
            self._max_drain_waves = 1
        # Keys currently resident across the shard tables (== unique_count
        # until the first eviction).
        self._l0_count = 0
        self._wave_stale = 0

        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._discoveries_fp: Dict[str, int] = {}
        self._wave_log: List = []
        # Under symmetry: the u64 visited-set keys claimed so far (the
        # checkpoint rebuild needs them; original fps cannot be re-keyed).
        self._key_log: List = []
        self._store = make_fingerprint_store()
        self._ingested = 0
        self._ingest_lock = threading.Lock()
        self._done_event = threading.Event()
        self._error: Optional[BaseException] = None
        # Preemption (checking-as-a-service): wave/drain-boundary yield
        # points drain the run into an in-memory checkpoint payload —
        # same API as TpuBfsChecker (see checker/base.py).
        self._preempt_event = threading.Event()
        self._preempt_payload: Optional[dict] = None
        # Async pipelined wave engine (README "Async pipeline"; see
        # TpuBfsChecker for the full design note). Sharded twist: the
        # host pool COALESCES rows into chunks, so the loop may only
        # dispatch ahead of in-flight verdicts while the pool already
        # holds a full chunk without them — below that, the epoch
        # barrier restores the synchronous composition (the partial
        # overlap is exactly the wide-frontier regime where the probe
        # is expensive). The pool therefore gains a lock: the worker
        # appends survivors while the checker thread slices chunks.
        self._async = bool(async_pipeline)
        if self._async and self._visitor is not None:
            raise ValueError(
                "async_pipeline is incompatible with a visitor: per-chunk "
                "callbacks reconstruct paths through verdicts the "
                "pipeline defers; drop the visitor or run synchronously"
            )
        if self._async and jax.process_count() > 1:
            raise ValueError(
                "async_pipeline is single-controller only: deferred "
                "verdicts issue process_allgather collectives from the "
                "worker thread, which cannot be ordered against the "
                "checker thread's across processes"
            )
        self._pipe = (
            HostPipeline(name="sharded-bfs-host") if self._async else None
        )
        self._pool_lock = threading.Lock()
        # In-flight harvest verdicts (jobs that may still append pool
        # rows) — the coalescing barrier's predicate. Deferred
        # checkpoint pickles and evict absorbs never grow the pool, so
        # they must not re-serialize the loop (pool_lock-guarded).
        self._inflight_verdicts = 0

        self._shard = NamedSharding(self._mesh, P("fp"))
        self._replicated = NamedSharding(self._mesh, P())
        # Multi-controller (multi-host) mode: under ``jax.distributed`` the
        # mesh spans processes and device arrays are only partially
        # addressable from each host — host pulls must allgather.
        self._mp = jax.process_count() > 1
        # Buffer donation on the jitted collectives mirrors TpuBfsChecker:
        # the hash-table shards and pool rings are rebound to the returned
        # arrays by every caller, so the per-call copy of the largest
        # operands disappears. The export path (_jit_ring_export) is
        # deliberately NOT donated — checkpoints read the rings mid-run
        # and the pool must survive the call.
        # With the sieve on, the wave and deep drain carry two extra
        # sharded operands (receipt cache + Bloom filter) that are
        # donated and rebound every call, like the table.
        wave_in = (P("fp"),) * 7 + (P(),)
        wave_donate = (0,)
        deep_in = (P("fp"),) * 4 + (P(), P(), P())
        deep_donate = (0, 1)
        if self._sieve:
            wave_in = wave_in + (P("fp"), P("fp"))
            wave_donate = (0, 8, 9)
            deep_in = deep_in + (P("fp"), P("fp"))
            deep_donate = (0, 1, 7, 8)
        self._jit_wave = jax.jit(
            shard_map(
                self._wave_local,
                mesh=self._mesh,
                in_specs=wave_in,
                out_specs=P("fp"),
                check_vma=False,
            ),
            donate_argnums=wave_donate,
        )
        self._wave_exec = {}  # (local capacity, chunk width) -> AOT wave
        # Disk tier of the wave-executable cache (warm-start plane,
        # storage/persist.py): bound lazily at the first wave dispatch —
        # the trace-relevant attributes (liveness, coverage, sieve) are
        # not all set yet at this point in __init__. The deep drain is
        # NOT disk-cached here: its compile site pre-compiles inline and
        # dispatches through the jit object, so there is no executable
        # handle to persist without restructuring the drain loop.
        self._aot_store_arg = aot_store
        self._aot_disk = None
        self._jit_insert = jax.jit(
            shard_map(
                self._insert_local,
                mesh=self._mesh,
                in_specs=(P("fp"),) * 4,
                out_specs=P("fp"),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        # Only the destination table (arg 1) can alias the output; the
        # old, smaller table is freed by the caller's rebind.
        self._jit_rehash = jax.jit(
            shard_map(
                self._rehash_local,
                mesh=self._mesh,
                in_specs=(P("fp"), P("fp")),
                out_specs=P("fp"),
                check_vma=False,
            ),
            donate_argnums=(1,),
        )
        self._jit_deep_drain = jax.jit(
            shard_map(
                self._deep_drain_local,
                mesh=self._mesh,
                in_specs=deep_in,
                out_specs=P("fp"),
                check_vma=False,
            ),
            donate_argnums=deep_donate,
        )
        self._jit_ring_push = jax.jit(
            shard_map(
                self._push_local,
                mesh=self._mesh,
                in_specs=(P("fp"),) * 4,
                out_specs=P("fp"),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        self._jit_ring_export = jax.jit(
            shard_map(
                self._ring_export_local,
                mesh=self._mesh,
                in_specs=(P("fp"),) * 3,
                out_specs=P("fp"),
                check_vma=False,
            )
        )
        # Fingerprints go through the model's view hook (e.g. actor systems
        # exclude crash flags, mirroring the host state hash).
        self._fp_fn = model.packed_fingerprint
        # Visited/routing keys: orbit-minimum fingerprints under symmetry
        # reduction (see checker/tpu.py and core/batch.py).
        self._symmetry_enabled = options._symmetry is not None
        self._sym_scheme = sym_key_scheme(options._symmetry)
        self._key_fn = _make_key_fn(model, self._fp_fn, options._symmetry)
        # Device-native liveness (liveness="device"; see checker/tpu.py
        # and README "Trustworthy liveness"). Sharded twist: the edge
        # rows ride each wave's sharded output and are absorbed at the
        # harvest exit the wave already pays (the sharded drain has no
        # per-wave host exit to evict through, so liveness forces the
        # wave-at-a-time path — same clamp as out-of-core mode).
        from ..checker.device_liveness import validate_liveness_mode

        self._live = validate_liveness_mode(
            liveness,
            symmetry=self._symmetry_enabled,
            expand_fps=False,
            options=options,
        )
        self._live_enabled = self._live == "device" and bool(self._ebit)
        self._live_paths: Dict[str, Path] = {}
        self._live_outcomes: Dict[str, dict] = {}
        self._live_store = None
        if self._live_enabled:
            from ..storage import LivenessEdgeStore, LivenessInstruments

            self._max_drain_waves = 1
            self._live_ins = LivenessInstruments(
                "sharded_bfs", registry=self._registry
            )
            self._live_store = LivenessEdgeStore(
                instruments=self._live_ins, spill_dir=spill_dir,
                host_budget_mib=host_budget_mib,
            )
        self._jit_fp_batch = jax.jit(jax.vmap(self._fp_fn))
        self._jit_key_batch = (
            jax.jit(self._key_fn)
            if self._symmetry_enabled
            else self._jit_fp_batch
        )
        self._jit_fp_single = jax.jit(self._fp_fn)

        # Telemetry: one span per host-visible wave/drain (see
        # stateright_tpu.telemetry); occupancy is global across shards.
        # (Tracer/registry already bound above — run_id-scoped when set.)
        self._wi = WaveInstruments("sharded_bfs", registry=self._registry)
        # Cross-shard exchange ledger — recorded sieve-on AND sieve-off
        # (the unsieved wave ships the full width), so A/B runs compare
        # lanes/bytes like for like.
        self._ci = CommsInstruments("sharded_bfs", registry=self._registry)
        if self._fleet_on:
            from ..telemetry.fleet import FleetInstruments

            self._fi = FleetInstruments(
                "sharded_bfs", n, registry=self._registry,
                hosts=jax.process_count(),
            )
        # Wave-timeline attribution (opt-in, telemetry/attribution.py):
        # same engine and phase names as TpuBfsChecker, prefixed
        # ``sharded_bfs`` — results stay bit-identical (fences change
        # pacing only).
        self._init_attribution("sharded_bfs", attribution)
        if self._attr is not None and self._async:
            self._attr.set_overlap_mode(True)
        # State-space cartography (opt-in, telemetry/coverage.py): the
        # same fused reductions as TpuBfsChecker, computed per shard
        # inside the wave/drain shard_maps and summed across the mesh at
        # the existing host exits. coverage=False traces no extra ops.
        self._init_coverage(
            "sharded_bfs", coverage, self._A,
            symmetry=self._symmetry_enabled,
        )
        self.donation_enabled = True

        self._handles = [
            threading.Thread(target=self._run, name="sharded-tpu-bfs", daemon=True)
        ]
        self._handles[0].start()

    # -- per-device kernels (inside shard_map) ----------------------------

    def _route_insert(self, table_loc, hi, lo, valid):
        """Key exchange + sharded claim-insert; returns
        (table, fresh, overflow, recv_uniq).

        ``hi/lo/valid`` are this device's local candidate keys (m lanes).
        ``fresh`` marks, per local lane, that *this* lane's key claimed a
        brand-new slot somewhere in the global set. Exactly one lane wins
        per distinct key across the whole mesh.
        """
        n = self._n
        m = hi.shape[0]
        owner = (hi % jnp.uint32(n)).astype(jnp.int32)

        # Vectorized owner bucketing: one stable sort groups lanes by owner
        # (invalid lanes to a sentinel bucket), the within-bucket offset is
        # index-minus-group-start via cummax, and three scatters place the
        # keys — compile cost stays flat as the mesh grows instead of
        # emitting n cumsum+scatter rounds.
        lanes = jnp.arange(m, dtype=jnp.int32)
        okey = jnp.where(valid, owner, n)
        okey_s, lane_s = jax.lax.sort((okey, lanes), num_keys=1)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), okey_s[1:] != okey_s[:-1]]
        )
        group_start = jax.lax.cummax(jnp.where(is_start, lanes, 0))
        pos = lanes - group_start
        table_loc, fresh, _ack, overflow, recv_uniq = self._exchange_at(
            table_loc, hi[lane_s], lo[lane_s], lane_s, okey_s, pos, m, m,
            want_ack=False,
        )
        return table_loc, fresh, overflow, recv_uniq

    def _exchange_at(
        self, table_loc, hi_s, lo_s, lane_s, okey_s, pos, R, m,
        want_ack=False,
    ):
        """The owner exchange + claim-insert at per-destination width
        ``R`` (``R == m`` reproduces the historical full-width exchange
        op for op). Inputs are the owner-sorted keys with within-group
        offsets; outputs are per ORIGINAL lane.

        Returns ``(table, fresh, acked, overflow, recv_uniq)``.
        ``recv_uniq`` is the OWNER-side insert load: how many unique keys
        arrived at THIS shard's table in the exchange (the fleet skew
        ledger's hash-partition imbalance column — free, the dedup mask
        already exists). ``acked``
        (``want_ack=True`` — the sieved path) marks lanes whose key is
        provably resident at its owner after this exchange: claimed fresh
        OR already found, but NOT probe-cap overflow. That is exactly the
        receipt-cache admission condition — caching a pending
        (overflowed) lane would kill its retry after the host grows the
        table and lose the state. ``want_ack=False`` returns ``None`` for
        it and keeps the legacy bool flag exchange untouched.
        """
        n = self._n
        dest = jnp.where((okey_s < n) & (pos < R), okey_s * R + pos, n * R)
        send_hi = (
            jnp.zeros((n * R,), jnp.uint32)
            .at[dest]
            .set(hi_s, mode="drop")
            .reshape(n, R)
        )
        send_lo = (
            jnp.zeros((n * R,), jnp.uint32)
            .at[dest]
            .set(lo_s, mode="drop")
            .reshape(n, R)
        )
        src_slot = (
            jnp.full((n * R,), m, jnp.int32)
            .at[dest]
            .set(lane_s, mode="drop")
            .reshape(n, R)
        )

        recv_hi = jax.lax.all_to_all(
            send_hi, "fp", split_axis=0, concat_axis=0, tiled=True
        )
        recv_lo = jax.lax.all_to_all(
            send_lo, "fp", split_axis=0, concat_axis=0, tiled=True
        )

        rhi = recv_hi.reshape(n * R)
        rlo = recv_lo.reshape(n * R)
        # (0, 0) is the bucket padding sentinel; fingerprints are never (0,0).
        ractive = (rhi != 0) | (rlo != 0)
        shi, slo, sidx, uniq = _sort_dedup(rhi, rlo, ractive)
        table_loc, fresh_s, found_s, pending = hashset_insert(
            table_loc, shi, slo, uniq
        )
        overflow = pending.sum()
        recv_uniq = uniq.sum(dtype=jnp.int32)
        if want_ack:
            # Pack (fresh, resident) into one uint8 so the reverse
            # exchange stays a single collective.
            flags_s = fresh_s.astype(jnp.uint8) | (
                (fresh_s | found_s).astype(jnp.uint8) << 1
            )
            flags_r = (
                jnp.zeros((n * R,), jnp.uint8)
                .at[sidx]
                .set(flags_s)
                .reshape(n, R)
            )
            back = jax.lax.all_to_all(
                flags_r, "fp", split_axis=0, concat_axis=0, tiled=True
            ).reshape(-1)
            fl = (
                jnp.zeros((m,), jnp.uint8)
                .at[src_slot.reshape(-1)]
                .set(back, mode="drop")
            )
            return table_loc, (fl & 1) != 0, (fl & 2) != 0, overflow, recv_uniq
        # Un-sort fresh flags back to received order, then reverse-exchange.
        fresh_r = (
            jnp.zeros((n * R,), bool).at[sidx].set(fresh_s).reshape(n, R)
        )
        fresh_back = jax.lax.all_to_all(
            fresh_r, "fp", split_axis=0, concat_axis=0, tiled=True
        )
        fresh = (
            jnp.zeros((m,), bool)
            .at[src_slot.reshape(-1)]
            .set(fresh_back.reshape(-1), mode="drop")
        )
        return table_loc, fresh, None, overflow, recv_uniq

    def _comm_rungs(self, m):
        """Ascending per-destination exchange widths for an ``m``-lane
        wave: a base-4 ladder from 8 lanes up (8, 32, 128, ...) capped by
        the always-sufficient full width ``m`` (the bucket-ladder idiom
        from the chunk dispatcher). Base 4 bounds overshoot at 4x the
        survivor count while keeping the ``lax.switch`` branch count —
        each branch traces its own all_to_all pair — at log4(m). One
        mesh-agreed index (``pmax``) picks the rung, so peers never
        diverge on the collective shape."""
        rungs = []
        r = 8
        while r < m:
            rungs.append(r)
            r <<= 2
        return rungs + [m]

    def _route_insert_sieved(self, table_loc, hi, lo, valid, cache, bloom):
        """``_route_insert`` with the sieve + compact stages in front of
        the collective (ISSUE 17 tentpole; returns the updated sieve
        state and the wave's comms vector alongside).

        **Sieve** — the receipt cache re-checks the FULL key on a hit, so
        a kill is a proof this device already routed the key and its
        owner acked residency: the full-width exchange would answer
        ``fresh=False``, which is precisely what a dropped lane reports.
        No false positive exists to repair, so per-lane results are
        bit-identical by construction. The Bloom filter over the same
        routed keys never drops anything — it is the audited advisory
        layer: for a routed lane the owner's verdict IS an exact
        membership re-check, so ``bloom_hit & fresh`` counts true Bloom
        false positives with zero extra probes.

        **Compact** — survivors pack to a dense per-destination prefix
        and the exchange runs at the smallest ladder rung holding the
        mesh-max survivor count; every device takes the same
        ``lax.switch`` branch (the rung index is a ``pmax``), so the
        collectives inside the branches always match up.
        """
        n = self._n
        m = hi.shape[0]
        kill = comm_sieve.cache_probe(cache, hi, lo, valid)
        bhit = comm_sieve.bloom_probe(bloom, hi, lo)
        send = valid & ~kill

        owner = (hi % jnp.uint32(n)).astype(jnp.int32)
        lanes = jnp.arange(m, dtype=jnp.int32)
        okey = jnp.where(send, owner, n)
        okey_s, lane_s = jax.lax.sort((okey, lanes), num_keys=1)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), okey_s[1:] != okey_s[:-1]]
        )
        group_start = jax.lax.cummax(jnp.where(is_start, lanes, 0))
        pos = lanes - group_start
        hi_s = hi[lane_s]
        lo_s = lo[lane_s]
        counts = jnp.zeros((n + 1,), jnp.int32).at[okey].add(1)
        need = jax.lax.pmax(counts[:n].max(), "fp")
        rungs = self._comm_rungs(m)
        if len(rungs) == 1:
            ridx = jnp.int32(0)
            table_loc, fresh, ack, overflow, recv_uniq = self._exchange_at(
                table_loc, hi_s, lo_s, lane_s, okey_s, pos, m, m,
                want_ack=True,
            )
        else:
            # Smallest rung >= need; the top rung is m >= any count.
            ridx = (need > jnp.asarray(rungs, jnp.int32)).sum().astype(
                jnp.int32
            )
            branches = [
                (
                    lambda R: lambda tbl, a, b, c, d, e: self._exchange_at(
                        tbl, a, b, c, d, e, R, m, want_ack=True
                    )
                )(R)
                for R in rungs
            ]
            table_loc, fresh, ack, overflow, recv_uniq = jax.lax.switch(
                ridx, branches, table_loc, hi_s, lo_s, lane_s, okey_s, pos
            )
        # Receipts: only owner-acked lanes (see _exchange_at) enter the
        # cache and filter — after this wave those keys ARE resident.
        acked = send & ack
        cache = comm_sieve.cache_insert(cache, hi, lo, acked)
        bloom = comm_sieve.bloom_insert(bloom, hi, lo, acked)
        shipped = n * jnp.asarray(rungs, jnp.int32)[ridx]
        comms = jnp.concatenate(
            [
                jnp.stack(
                    [
                        valid.sum(dtype=jnp.int32),
                        kill.sum(dtype=jnp.int32),
                        send.sum(dtype=jnp.int32),
                        (bhit & send).sum(dtype=jnp.int32),
                        # Exact Bloom FPs: hit, routed, owner says fresh.
                        (bhit & send & fresh).sum(dtype=jnp.int32),
                        shipped,
                    ]
                ),
                (jnp.arange(len(rungs), dtype=jnp.int32) == ridx).astype(
                    jnp.int32
                ),
            ]
        )
        return table_loc, fresh, overflow, cache, bloom, comms, recv_uniq

    def _insert_local(self, table, hi, lo, valid):
        """Standalone sharded insert (used to seed the initial states)."""
        table_loc, fresh, overflow, _recv = self._route_insert(
            table[0], hi, lo, valid
        )
        return {
            "table": table_loc[None],
            "fresh": fresh,
            "overflow": overflow[None],
        }

    def _wave_local(
        self, table, states, hi, lo, ebits, depth, mask, depth_cap,
        cache=None, bloom=None,
    ):
        """shard_map wrapper: unwraps the leading per-device axis, runs the
        wave core, and re-wraps scalars for ``out_specs=P("fp")``."""
        out = self._wave_core(
            table[0], states, hi, lo, ebits, depth, mask, depth_cap,
            cache=None if cache is None else cache[0],
            bloom=None if bloom is None else bloom[0],
        )
        wrapped = dict(out)
        wrapped["table"] = out["table"][None]
        for k in ("generated", "n_new", "overflow", "max_depth"):
            wrapped[k] = out[k][None]
        wrapped["comms"] = out["comms"][None]
        if self._fleet_on:
            wrapped["fleet"] = out["fleet"][None]
        if self._sieve:
            wrapped["sieve_cache"] = out["sieve_cache"][None]
            wrapped["sieve_bloom"] = out["sieve_bloom"][None]
        if self._properties:
            for k in ("prop_hit", "prop_hi", "prop_lo"):
                wrapped[k] = out[k][None]
        if self._cov is not None:
            wrapped["cov"] = out["cov"][None]
        if self._live_enabled:
            wrapped["live_n"] = out["live_n"][None]
        return wrapped

    def _wave_core(
        self, table_loc, states, hi, lo, ebits, depth, mask, depth_cap,
        cache=None, bloom=None,
    ):
        """One expansion wave on local (per-device) arrays: expand,
        fingerprint, pre-dedup, all-to-all claim-insert, compact. Scalars
        come back unwrapped; the deep drain and the wave-at-a-time wrapper
        share this."""
        model = self._model
        A = self._A
        F = hi.shape[0]  # local slice width
        B = F * A
        eval_mask = mask & (depth < depth_cap)

        cond_vals = [jax.vmap(c)(states) for c in self._conditions]
        ebits_after = ebits
        for pi, b in self._ebit.items():
            ebits_after = jnp.where(
                cond_vals[pi], ebits_after & ~jnp.uint32(1 << b), ebits_after
            )

        # packed_expand: per-class fast path where the model provides one.
        cand, cvalid = jax.vmap(model.packed_expand)(states)
        cvalid = cvalid & eval_mask[:, None]
        cvalid = cvalid & jax.vmap(jax.vmap(model.packed_within_boundary))(cand)
        generated = cvalid.sum(dtype=jnp.int32)
        terminal = eval_mask & ~cvalid.any(axis=1)

        cand_flat = jax.tree_util.tree_map(
            lambda x: x.reshape((B,) + x.shape[2:]), cand
        )
        cvalid_flat = cvalid.reshape(B)
        chi, clo = jax.vmap(self._fp_fn)(cand_flat)
        # Routing/visited keys (orbit-minimum fps under symmetry); frontier
        # rows and parent pointers keep the ORIGINAL fingerprints below.
        if self._symmetry_enabled:
            khi, klo = self._key_fn(cand_flat)
        else:
            khi, klo = chi, clo

        # Local pre-dedup: only one lane per distinct key is routed, so the
        # owner-side exchange carries no intra-device duplicates.
        _shi, _slo, sidx, uniq = _sort_dedup(khi, klo, cvalid_flat)
        route = jnp.zeros((B,), bool).at[sidx].set(uniq)
        if self._sieve:
            table_loc, fresh, overflow, cache, bloom, comms, recv_uniq = (
                self._route_insert_sieved(
                    table_loc, khi, klo, route, cache, bloom
                )
            )
        else:
            table_loc, fresh, overflow, recv_uniq = self._route_insert(
                table_loc, khi, klo, route
            )
            # Uniform comms vector (layout as _route_insert_sieved's):
            # the unsieved exchange ships the full n*B lanes per device
            # at a single full-width "rung" — emitted even sieve-off so
            # A/B runs compare ledgers like for like.
            comms = jnp.concatenate(
                [
                    jnp.zeros((5,), jnp.int32),
                    jnp.full((1,), self._n * B, jnp.int32),
                    jnp.ones((1,), jnp.int32),
                ]
            )

        # Compact fresh candidates into the local next-frontier slots.
        pos = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        out_slot = jnp.where(fresh, pos, B)
        zi = jnp.zeros((B,), jnp.int32)
        zu = jnp.zeros((B,), jnp.uint32)
        src_idx = zi.at[out_slot].set(
            jnp.arange(B, dtype=jnp.int32), mode="drop"
        )
        parent_row = src_idx // A
        new_states = jax.tree_util.tree_map(
            lambda x: x[src_idx], cand_flat
        )
        out = {
            "table": table_loc,
            "generated": generated,
            "n_new": fresh.sum(dtype=jnp.int32),
            "overflow": overflow,
            "max_depth": jnp.max(jnp.where(mask, depth, 0)),
            "new_states": new_states,
            "new_hi": zu.at[out_slot].set(chi, mode="drop"),
            "new_lo": zu.at[out_slot].set(clo, mode="drop"),
            "new_ebits": ebits_after[parent_row]
            * (jnp.arange(B) < fresh.sum()),
            "new_depth": (depth[parent_row] + 1)
            * (jnp.arange(B) < fresh.sum()),
            "parent_hi": hi[parent_row] * (jnp.arange(B) < fresh.sum()),
            "parent_lo": lo[parent_row] * (jnp.arange(B) < fresh.sum()),
            "comms": comms,
        }
        if self._fleet_on:
            # Per-shard skew vector (telemetry/fleet.py FLEET_DEVICE_COLS
            # order); stacked per device by out_specs=P("fp") so the
            # controller sees the (n, 5) mesh view every pull. Write-only
            # telemetry — nothing reads it back into the search.
            out["fleet"] = jnp.stack(
                [
                    eval_mask.sum(dtype=jnp.int32),
                    generated,
                    fresh.sum(dtype=jnp.int32),
                    recv_uniq,
                    overflow.astype(jnp.int32),
                ]
            )
        if self._sieve:
            out["sieve_cache"] = cache
            out["sieve_bloom"] = bloom
        if self._symmetry_enabled:
            # Claimed visited-set keys, for checkpoint table rebuild.
            out["new_khi"] = zu.at[out_slot].set(khi, mode="drop")
            out["new_klo"] = zu.at[out_slot].set(klo, mode="drop")

        if self._live_enabled:
            # Condition-false edge + terminal rows for this shard's
            # lanes (checker/device_liveness.py) — compacted per shard,
            # pulled at the harvest exit the wave already pays. The
            # parent fps are this device's frontier slice; duplicates
            # across shards dedup in the host store.
            from ..checker.device_liveness import wave_edge_rows

            live_rows, live_n = wave_edge_rows(
                self._conditions, self._ebit, cond_vals, cand_flat,
                cvalid_flat, terminal, hi, lo, chi, clo, A,
            )
            for c, col in live_rows.items():
                out[f"live_{c}"] = col
            out["live_n"] = live_n

        hits, fhis, flos = [], [], []
        for i, p in enumerate(self._properties):
            if p.expectation == Expectation.ALWAYS:
                h = eval_mask & ~cond_vals[i]
            elif p.expectation == Expectation.SOMETIMES:
                h = eval_mask & cond_vals[i]
            else:
                b = self._ebit[i]
                h = terminal & (((ebits_after >> jnp.uint32(b)) & 1) == 1)
            idx = jnp.argmax(h)
            hits.append(h.any())
            fhis.append(hi[idx])
            flos.append(lo[idx])
        if self._properties:
            out["prop_hit"] = jnp.stack(hits)
            out["prop_hi"] = jnp.stack(fhis)
            out["prop_lo"] = jnp.stack(flos)
        if self._cov is not None:
            # Per-shard coverage reduction (telemetry/coverage.py): the
            # host sums the per-device vectors at its existing exits.
            # ``fresh`` marks this shard's GENERATOR-side claim winners,
            # so per-action fresh attribution stays exact across the
            # mesh exchange.
            exercised = []
            for pi, p in enumerate(self._properties):
                if p.expectation == Expectation.ALWAYS:
                    ant = self._cov_antecedents[pi]
                    exercised.append(
                        eval_mask & jax.vmap(ant)(states)
                        if ant is not None
                        else eval_mask
                    )
                elif p.expectation == Expectation.SOMETIMES:
                    exercised.append(eval_mask & cond_vals[pi])
                else:
                    eb = self._ebit[pi]
                    exercised.append(
                        eval_mask
                        & (((ebits_after >> jnp.uint32(eb)) & 1) == 0)
                    )
            uniq_fp = uniq_key = None
            if self._symmetry_enabled:
                uniq_fp = self._cov_layout.count_distinct(
                    chi, clo, cvalid_flat
                )
                uniq_key = self._cov_layout.count_distinct(
                    khi, klo, cvalid_flat
                )
            lanes_b = jnp.arange(B, dtype=jnp.int32)
            out["cov"] = self._cov_layout.wave_reduce(
                eval_mask=eval_mask,
                cvalid=cvalid,
                fresh=fresh,
                lane_action=lanes_b % A,
                new_depth=depth[lanes_b // A] + 1,
                exercised=exercised,
                uniq_fp=uniq_fp,
                uniq_key=uniq_key,
            )
        return out

    def _rehash_local(self, old_table, new_table):
        old = old_table[0]
        new = new_table[0]
        active = (old[:, 0] != 0) | (old[:, 1] != 0)
        new, _fresh, _found, pending = hashset_insert(
            new, old[:, 0], old[:, 1], active
        )
        return {"table": new[None], "overflow": pending.sum()[None]}

    # -- deep drain: per-device frontier rings + all-to-all row balancing --

    def _ring_export_local(self, pool, head, count):
        """Local ring contents in FIFO order, mask attached (shard_map
        entry)."""
        return ring_export(pool, head[0], count[0], self._PCl)

    def _push_local(self, pool, head, count, rows):
        """shard_map entry: pushes a host chunk slice into the local ring."""
        pool, cnt = ring_push(
            pool, head[0], count[0], rows, rows["mask"], self._PCl
        )
        return {"pool": pool, "count": cnt[None]}

    def _balance_exchange(self, rows, n_new):
        """Round-robin all-to-all of the fresh (compacted-prefix) frontier
        rows: lane ``j`` goes to device ``j % n``. Fresh states are born on
        the device that expanded their parent; without this exchange a
        device that seeds the search keeps every descendant and the rest of
        the mesh idles. Round-robin balances by construction (each device
        receives within ±1 of the mean from every sender) with a fixed
        ``ceil(B/n)`` per-pair quota — no data-dependent shapes."""
        n = self._n
        B = rows["hi"].shape[0]
        q = -(-B // n)
        j = jnp.arange(B, dtype=jnp.int32)
        dest = jnp.where(j < n_new, (j % n) * q + j // n, n * q)

        def scat(x):
            z = jnp.zeros((n * q,) + x.shape[1:], x.dtype)
            return z.at[dest].set(x, mode="drop")

        def xch(x):
            return jax.lax.all_to_all(
                x.reshape((n, q) + x.shape[1:]),
                "fp",
                split_axis=0,
                concat_axis=0,
                tiled=True,
            ).reshape((n * q,) + x.shape[1:])

        send_mask = (
            jnp.zeros((n * q,), jnp.uint32)
            .at[dest]
            .set(jnp.ones((B,), jnp.uint32), mode="drop")
        )
        recv = {
            k: (
                jax.tree_util.tree_map(lambda x: xch(scat(x)), v)
                if k == "states"
                else xch(scat(v))
            )
            for k, v in rows.items()
        }
        recv_mask = xch(send_mask) != 0
        return recv, recv_mask

    def _drain_decide(self, out, count_after, log_n, budget, waves, gen_acc, undiscovered):
        """The globally-agreed continue flag (identical on every device —
        all inputs are psums or replicated)."""
        n_new = out["n_new"]
        g_n_new = jax.lax.psum(n_new, "fp")
        g_count = jax.lax.psum(count_after, "fp")
        g_overflow = jax.lax.psum(out["overflow"], "fp")
        ok = (g_n_new > 0) | (g_count > 0)
        ok &= g_overflow == 0
        if self._properties:
            hit = (out["prop_hit"] & undiscovered).any()
            ok &= jax.lax.psum(hit.astype(jnp.int32), "fp") == 0
        # Generator-side log room for appending this wave's fresh rows.
        no_log_room = (log_n + n_new > self._Ll).astype(jnp.int32)
        ok &= jax.lax.psum(no_log_room, "fp") == 0
        # Ring room for pushing the rows this device just received.
        recv_n = out["recv_mask"].sum(dtype=jnp.int32)
        no_ring_room = (count_after + recv_n > self._PCl).astype(jnp.int32)
        ok &= jax.lax.psum(no_ring_room, "fp") == 0
        ok &= budget - g_n_new >= jnp.int32(self._G * self._A)
        ok &= waves < self._max_drain_waves
        # gen_acc is a per-device local counter; the vote must be identical
        # on every device or one device exits the collective-bearing loop
        # while peers keep calling all_to_all (mesh hang). pmax (not psum:
        # a psum over many devices could itself wrap int32) exits when ANY
        # device's accumulator nears the wrap.
        ok &= jax.lax.pmax(gen_acc, "fp") < jnp.int32(1 << 30)
        return ok

    def _deep_drain_local(
        self, table, pool, head, count, undiscovered, budget, depth_cap,
        cache=None, bloom=None,
    ):
        """The sharded deep drain: consecutive waves inside one device
        ``while_loop``. Each iteration appends the previous wave's fresh
        rows to the parent-fp log (generator side), pushes the rows this
        device *received* in the balance exchange onto its ring, dequeues
        the next local frontier, and expands it. Exit is a global vote
        (psum) — log full, ring full, table budget, hash overflow, or an
        undiscovered property hit — mirroring ``TpuBfsChecker``'s deep
        drain with collectives in place of single-device checks."""
        F, A, n = self._F_loc, self._A, self._n
        B = F * A
        Ll = self._Ll

        table_loc = table[0]
        head0 = head[0]
        count0 = count[0]
        budget0 = budget
        cache0 = None if cache is None else cache[0]
        bloom0 = None if bloom is None else bloom[0]

        def wave_plus(tbl, fr, cache, bloom):
            out = self._wave_core(
                tbl,
                fr["states"],
                fr["hi"],
                fr["lo"],
                fr["ebits"],
                fr["depth"],
                fr["mask"],
                depth_cap,
                cache=cache,
                bloom=bloom,
            )
            rows = {
                "states": out["new_states"],
                "hi": out["new_hi"],
                "lo": out["new_lo"],
                "ebits": out["new_ebits"],
                "depth": out["new_depth"],
            }
            recv, recv_mask = self._balance_exchange(rows, out["n_new"])
            out["recv"] = recv
            out["recv_mask"] = recv_mask
            return out

        fr0, head1, count1 = ring_take(
            {k: pool[k] for k in ("states", "hi", "lo", "ebits", "depth")},
            head0,
            count0,
            self._PCl,
            F,
        )
        out0 = wave_plus(table_loc, fr0, cache0, bloom0)
        zl = jnp.zeros((Ll,), jnp.uint32)
        log0 = {
            "child_hi": zl,
            "child_lo": zl,
            "parent_hi": zl,
            "parent_lo": zl,
        }
        if self._symmetry_enabled:
            log0.update(key_hi=zl, key_lo=zl)
        carry = {
            "pool": {k: pool[k] for k in ("states", "hi", "lo", "ebits", "depth")},
            "head": head1,
            "count": count1,
            "frontier": fr0,
            "out": out0,
            "log": log0,
            "log_n": jnp.int32(0),
            "generated": jnp.int32(0),
            "consumed_unique": jnp.int32(0),
            "max_depth": jnp.int32(0),
            # int32 is fine here: lanes/wave × waves/drain stays well
            # under 2^31 for any budget-bounded drain, and the vector is
            # telemetry only — never feeds back into results.
            "comms_acc": jnp.zeros_like(out0["comms"]),
            "budget": budget0,
            **(
                {"fleet_acc": jnp.zeros_like(out0["fleet"])}
                if self._fleet_on
                else {}
            ),
            # The pre-loop wave (out0) counts against the cap too, so a
            # drain runs at most max_drain_waves waves total (the cap backs
            # the checkpoint-durability guarantee).
            "waves": jnp.int32(1),
            "go": self._drain_decide(
                out0, count1, jnp.int32(0), budget0, jnp.int32(1),
                jnp.int32(0), undiscovered,
            ),
        }
        if self._cov is not None:
            carry["cov_acc"] = jnp.zeros(
                (self._cov_layout.size,), jnp.int32
            )

        def cond(c):
            return c["go"]

        def body(c):
            o = c["out"]
            n_new = o["n_new"]
            lanes = jnp.arange(B, dtype=jnp.int32)
            valid = lanes < n_new
            slot = jnp.where(valid, c["log_n"] + lanes, Ll)
            log = dict(c["log"])
            log["child_hi"] = log["child_hi"].at[slot].set(
                o["new_hi"], mode="drop"
            )
            log["child_lo"] = log["child_lo"].at[slot].set(
                o["new_lo"], mode="drop"
            )
            log["parent_hi"] = log["parent_hi"].at[slot].set(
                o["parent_hi"], mode="drop"
            )
            log["parent_lo"] = log["parent_lo"].at[slot].set(
                o["parent_lo"], mode="drop"
            )
            if self._symmetry_enabled:
                log["key_hi"] = log["key_hi"].at[slot].set(
                    o["new_khi"], mode="drop"
                )
                log["key_lo"] = log["key_lo"].at[slot].set(
                    o["new_klo"], mode="drop"
                )
            pool, count = ring_push(
                c["pool"], c["head"], c["count"], o["recv"], o["recv_mask"],
                self._PCl,
            )
            frontier, head, count = ring_take(
                pool, c["head"], count, self._PCl, F
            )
            out = wave_plus(
                o["table"],
                frontier,
                o["sieve_cache"] if self._sieve else None,
                o["sieve_bloom"] if self._sieve else None,
            )
            log_n = c["log_n"] + n_new
            budget = c["budget"] - jax.lax.psum(n_new, "fp")
            waves = c["waves"] + 1
            gen_acc = c["generated"] + o["generated"]
            nxt = {
                "pool": pool,
                "head": head,
                "count": count,
                "frontier": frontier,
                "out": out,
                "log": log,
                "log_n": log_n,
                "generated": gen_acc,
                "consumed_unique": c["consumed_unique"] + n_new,
                "max_depth": jnp.maximum(c["max_depth"], o["max_depth"]),
                "comms_acc": c["comms_acc"] + o["comms"],
                "budget": budget,
                **(
                    {"fleet_acc": c["fleet_acc"] + o["fleet"]}
                    if self._fleet_on
                    else {}
                ),
                "waves": waves,
                "go": self._drain_decide(
                    out, count, log_n, budget, waves, gen_acc, undiscovered
                ),
            }
            if self._cov is not None:
                nxt["cov_acc"] = c["cov_acc"] + o["cov"]
            return nxt

        res = jax.lax.while_loop(cond, body, carry)
        o = res["out"]
        out = {
            "pool": res["pool"],
            "head": res["head"][None],
            "count": res["count"][None],
            "frontier": res["frontier"],
            "final": {
                "table": o["table"][None],
                "recv": o["recv"],
                "recv_mask": o["recv_mask"],
                "new_hi": o["new_hi"],
                "new_lo": o["new_lo"],
                "parent_hi": o["parent_hi"],
                "parent_lo": o["parent_lo"],
            },
            "drain_stats": jnp.stack(
                [
                    res["log_n"],
                    res["generated"],
                    res["consumed_unique"],
                    res["max_depth"],
                    res["waves"],
                    res["count"],
                    o["n_new"],
                    o["generated"],
                    o["overflow"],
                    o["max_depth"],
                ]
            )[None],
            # Consumed waves' exchange totals plus the final (unconsumed)
            # wave's — same accounting boundary as cov_acc below.
            "comms_acc": (res["comms_acc"] + o["comms"])[None],
        }
        if self._fleet_on:
            out["fleet_acc"] = (res["fleet_acc"] + o["fleet"])[None]
        if self._sieve:
            out["final"]["sieve_cache"] = o["sieve_cache"][None]
            out["final"]["sieve_bloom"] = o["sieve_bloom"][None]
        if self._symmetry_enabled:
            out["final"]["new_khi"] = o["new_khi"]
            out["final"]["new_klo"] = o["new_klo"]
        if self._cov is not None:
            # Consumed waves' accumulator plus the final (unconsumed)
            # wave: the final wave's expansion is complete device-side —
            # only its fresh rows' bookkeeping happens in _consume_final,
            # and an overflow retry there records fresh-based slices only.
            out["cov_acc"] = (res["cov_acc"] + o["cov"])[None]
        cols = ["child_hi", "child_lo", "parent_hi", "parent_lo"]
        if self._symmetry_enabled:
            cols += ["key_hi", "key_lo"]
        out["log_pack"] = jnp.stack([res["log"][c] for c in cols], axis=0)[
            None
        ]
        if self._properties:
            out["prop_hit"] = o["prop_hit"][None]
            out["prop_hi"] = o["prop_hi"][None]
            out["prop_lo"] = o["prop_lo"][None]
        return out

    # -- host side ---------------------------------------------------------

    def _run(self):
        try:
            self._explore()
        except BaseException as e:  # noqa: BLE001 - surfaced via worker_error
            self._error = e
            self._abort_attribution()
        finally:
            self._shutdown_pipeline()
            self._finalize_coverage(set(self._discoveries_fp))
            self._done_event.set()

    def _new_table(self):
        # Allocate pre-sharded: materializing the global table on one device
        # first would OOM exactly when shards are sized near per-device HBM.
        # Each shard carries the probe apron the hashset ops expect.
        return jax.jit(
            lambda: jnp.zeros(
                (self._n, self._cap_loc + MAX_PROBES, 2), jnp.uint32
            ),
            out_shardings=self._shard,
        )()

    def _new_sieve(self):
        """Cold (flushed) sieve state, pre-sharded: one receipt cache and
        one Bloom filter per device. Cold is always safe — kills only
        become possible again as keys are re-routed and re-acked."""
        return (
            jax.jit(
                lambda: jnp.zeros(
                    (self._n, self._sieve_slots, 2), jnp.uint32
                ),
                out_shardings=self._shard,
            )(),
            jax.jit(
                lambda: jnp.zeros((self._n, self._sieve_bits), jnp.uint8),
                out_shardings=self._shard,
            )(),
        )

    def _grow_table(self, table, min_cap_loc, defer_evict=False):
        """Grows (or, under an HBM budget, evicts) every shard's table.
        ``defer_evict=True`` — async wave loop only — hands the tier
        absorbs to the pipeline worker; the restore path keeps them
        synchronous (it probes the tiers from the checker thread)."""
        if (
            self._max_cap_loc is not None
            and min_cap_loc > self._max_cap_loc
        ):
            return self._evict_shards(table, defer=defer_evict)
        while self._cap_loc < min_cap_loc:
            self._cap_loc *= 2
        while True:
            with self._phase("table_grow"):
                out = self._jit_rehash(table, self._new_table())
                overflowed = int(self._pull(out["overflow"]).sum())
            if not overflowed:
                break
            # Probe-cap overflow during rehash costs capacity (retry at
            # the next doubling), never the run; under a budget the next
            # doubling may not exist — evict instead.
            self._cap_loc *= 2
            if (
                self._max_cap_loc is not None
                and self._cap_loc > self._max_cap_loc
            ):
                return self._evict_shards(table, defer=defer_evict)
        return out["table"]

    def _audit_table(self, table):
        """Run-end probe-length audit over every shard's table (summed —
        the shards share one hash scheme, so one distribution describes
        them all). Attribution mode only: the pull is a full-table read."""
        if self._attr is None:
            return
        from ..ops.hashset import hashset_probe_length_counts

        tab = self._pull(table)  # (n, cap_loc + apron, 2)
        counts = None
        for d in range(self._n):
            c = hashset_probe_length_counts(tab[d])
            counts = c if counts is None else counts + c
        self._attr.observe_probe_lengths(counts)

    def _tier_active(self) -> bool:
        return any(not t.is_empty() for t in self._tiers)

    def _evict_shards(self, table, defer=False):
        """Budget-capped growth: every shard's table drains to its own
        host tier (keys stay mesh-partitioned) and the sharded set
        resets at the budget cap. ``defer=True`` (async wave loop): the
        table pull + reset stay device-serial here; the per-shard
        absorbs ride the pipeline worker in shard order, fenced FIFO
        between the surrounding wave verdicts (see TpuBfsChecker.
        _evict_l0)."""
        with self._phase("evict"):
            if self._mp:
                # Compress stage (ISSUE 17): each process delta-encodes
                # ITS shards' live keys with the storage/runs.py wire
                # codec and the hosts exchange the compressed buffers —
                # a few bytes per key over DCN instead of allgathering
                # 8 B for every table slot, empty or not.
                shard_keys = self._allgather_evicted_keys(table)
            else:
                tab = self._pull(table)  # (n, cap_loc + apron, 2)
                shard_keys = []
                for d in range(self._n):
                    sh = tab[d]
                    live = (sh[:, 0] != 0) | (sh[:, 1] != 0)
                    keys = (
                        sh[live, 0].astype(np.uint64) << np.uint64(32)
                    ) | sh[live, 1].astype(np.uint64)
                    shard_keys.append(keys)
            if defer and self._pipe is not None:
                self._pipe.submit(
                    lambda ks=shard_keys: self._evict_absorb(ks)
                )
            else:
                for d, keys in enumerate(shard_keys):
                    t0 = time.perf_counter()
                    self._tiers[d].evict(keys)
                    self._fleet_note_evict(
                        d, time.perf_counter() - t0, keys.nbytes
                    )
            self._cap_loc = self._max_cap_loc
            self._l0_count = 0
            self._si.set_l0(0)
            if self._sieve and self._sieve_dev is not None:
                # Flush: receipts must only cover keys resident in the
                # DEVICE tables. An evicted key re-routed later gets
                # fresh=True from the unsieved exchange (the host-side
                # tier probe filters it); a stale receipt would kill
                # that lane and diverge from the sieve-off run.
                self._sieve_dev = self._new_sieve()
            return self._new_table()

    def _allgather_evicted_keys(self, table):
        """Multi-controller eviction exchange: local shard-key extraction
        plus a delta-compressed two-step allgather (lengths, then padded
        byte rows). Every process returns the identical per-shard sorted
        key lists, keeping the tier evictions SPMD across hosts."""
        from jax.experimental import multihost_utils

        from ..storage.runs import decode_sorted_fps, encode_sorted_fps

        n = self._n
        bufs = [b""] * n
        for sh in table.addressable_shards:
            d = sh.index[0].start or 0
            data = np.asarray(sh.data)[0]  # (cap_loc + apron, 2)
            live = (data[:, 0] != 0) | (data[:, 1] != 0)
            keys = (
                data[live, 0].astype(np.uint64) << np.uint64(32)
            ) | data[live, 1].astype(np.uint64)
            keys.sort()
            bufs[d] = encode_sorted_fps(keys)
        lens = np.array([len(b) for b in bufs], np.int64)
        all_lens = np.asarray(
            multihost_utils.process_allgather(lens)
        ).reshape(-1, n)
        width = max(1, int(all_lens.max()))
        pad = np.zeros((n, width), np.uint8)
        for d, b in enumerate(bufs):
            pad[d, : len(b)] = np.frombuffer(b, np.uint8)
        all_bufs = np.asarray(
            multihost_utils.process_allgather(pad)
        ).reshape(-1, n, width)
        shard_keys = []
        wire_bytes = 0
        for d in range(n):
            # Exactly one process owns shard d (its row is the only
            # non-empty one; an empty shard still carries the codec
            # header, so ownership is unambiguous).
            p = int(all_lens[:, d].argmax())
            ln = int(all_lens[p, d])
            shard_keys.append(decode_sorted_fps(all_bufs[p, d, :ln].tobytes()))
            wire_bytes += ln
        self._ci.evict_wire_bytes.inc(wire_bytes)
        self._tracer.instant(
            "sharded_bfs.evict_wire",
            bytes=wire_bytes,
            raw_bytes=int(table.shape[0]) * int(table.shape[1]) * 8,
            keys=int(sum(len(k) for k in shard_keys)),
        )
        return shard_keys

    def _evict_absorb(self, shard_keys):
        """Pipeline-worker half of a deferred eviction (all shards)."""
        with self._phase_overlapped("evict"):
            for d, keys in enumerate(shard_keys):
                t0 = time.perf_counter()
                self._tiers[d].evict(keys)
                self._fleet_note_evict(
                    d, time.perf_counter() - t0, keys.nbytes
                )

    def _probe_tiers(self, keys):
        """Union membership over every shard's store (L1 then L2 inside
        each; Bloom filters reject non-owner probes in O(1))."""
        found = np.zeros(len(keys), bool)
        for d, t in enumerate(self._tiers):
            rem = np.flatnonzero(~found)
            if not len(rem):
                break
            t0 = time.perf_counter()
            found[rem] = t.probe(keys[rem])
            if self._fleet_on:
                with self._fleet_lock:
                    self._fleet_probe_s[d] += time.perf_counter() - t0
        return found

    def _fleet_note_evict(self, d, seconds, nbytes):
        """Attributes one shard's tier-evict wall/bytes to the fleet
        ledger (called from both the sync loop and the pipeline worker)."""
        if not self._fleet_on:
            return
        with self._fleet_lock:
            self._fleet_evict_s[d] += seconds
            self._fleet_evict_bytes[d] += int(nbytes)

    def _pull(self, x):
        """A numpy view of a device array. Multi-controller: the array's
        shards live on several hosts, so gather them first (every process
        runs this same host loop in lockstep — SPMD over hosts — and gets
        identical values, keeping all host-side decisions consistent)."""
        if self._mp:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    def _put_sharded(self, x):
        """One host value onto the ``"fp"``-sharded layout. Every process
        passes the identical value (SPMD over hosts), so each one
        materializes just its addressable shards from it — device_put of
        an uncommitted array onto a non-fully-addressable sharding would
        instead broadcast-and-compare the full value through the
        coordination mesh per leaf per wave (jax's assert_equal guard),
        a collective storm the gloo DCN stand-in cannot keep in lockstep
        with the wave loop's own exchanges."""
        if self._mp:
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, self._shard, lambda idx, a=arr: a[idx]
            )
        return jax.device_put(jnp.asarray(x), self._shard)

    def _put_chunk(self, arrs):
        return jax.tree_util.tree_map(self._put_sharded, arrs)

    # The host pool is a deque of harvested row-batches; only the rows that
    # feed the next chunk are ever copied (a single running array would cost
    # O(frontier²/G) re-concatenation on big frontiers).

    @staticmethod
    def _rows_slice(batch, lo, hi):
        return {
            k: (
                jax.tree_util.tree_map(lambda x: x[lo:hi], v)
                if k == "states"
                else v[lo:hi]
            )
            for k, v in batch.items()
        }

    def _pool_append(self, rows):
        n = rows["hi"].shape[0]
        if n:
            # Locked: in async mode the pipeline worker appends
            # survivors while the checker thread slices chunks.
            with self._pool_lock:
                self._pool.append(rows)
                self._pool_count += n

    def _pool_take(self, width):
        """Pops up to ``width`` rows, padding to exactly ``width``."""
        parts = []
        got = 0
        with self._pool_lock:
            while got < width and self._pool:
                batch = self._pool.popleft()
                n = batch["hi"].shape[0]
                if got + n > width:
                    keep = width - got
                    self._pool.appendleft(self._rows_slice(batch, keep, n))
                    batch = self._rows_slice(batch, 0, keep)
                    n = keep
                parts.append(batch)
                got += n
            self._pool_count -= got

        def cat_pad(*xs):
            out = np.concatenate(xs) if len(xs) > 1 else np.asarray(xs[0])
            if out.shape[0] < width:
                pad = [(0, width - out.shape[0])] + [(0, 0)] * (out.ndim - 1)
                out = np.pad(out, pad)
            return out

        chunk = {
            k: (
                jax.tree_util.tree_map(cat_pad, *(p[k] for p in parts))
                if k == "states"
                else cat_pad(*(p[k] for p in parts))
            )
            for k in parts[0]
        }
        # The chunk splits into n contiguous per-device slices; interleave
        # real rows round-robin so a short chunk (got < width) gives every
        # shard ~got/n active lanes instead of idling the tail devices.
        n = self._n
        per = width // n
        dest = np.arange(width)
        src = (dest % per) * n + dest // per
        chunk = {
            k: (
                jax.tree_util.tree_map(lambda x: x[src], v)
                if k == "states"
                else v[src]
            )
            for k, v in chunk.items()
        }
        chunk["mask"] = src < got
        return chunk

    def _explore(self):
        self._t_start = time.perf_counter()
        # Wall-clock burned before the first drain/wave could run —
        # dominated by XLA compilation; benchmarks subtract it to report
        # steady-state rates (parity with TpuBfsChecker.warmup_seconds).
        self.warmup_seconds: Optional[float] = None
        self._pool = deque()
        self._pool_count = 0
        if self._resume_from is not None:
            table = self._restore(self._resume_from)
        else:
            table = self._seed()
        if self._sieve:
            # Cold sieve at run start — seed and resume alike. Receipts
            # only ever accumulate from keys THIS run routed and the
            # owner acked, which is the invariant the bit-identity
            # argument rests on; cold is always safe (no kills).
            self._sieve_dev = self._new_sieve()
        depth_cap = jnp.int32(self._depth_cap)
        # Deep drain is off for visitors, target counts, and depth caps:
        # ring scheduling is only approximately global-FIFO across devices,
        # so a depth-capped run could first reach a state via a longer path
        # and prune expansions a strict BFS would keep. (Without a cap the
        # visited SET is order-independent — counts stay exact.) The same
        # approximation means that even UNCAPPED sharded deep runs report
        # depth labels at first-claim: ``max_depth()`` and discovery-path
        # lengths are upper bounds on the true BFS values (the host,
        # single-device, and reference threaded-BFS checkers are the
        # minimal-depth yardstick); counts and property verdicts are exact
        # either way.
        if (
            self._max_drain_waves > 1
            and self._visitor is None
            and self._target_state_count is None
            and self._depth_cap == _DEPTH_INF
            # A resumed out-of-core run needs the per-wave host probe.
            and not (self._tiers and self._tier_active())
        ):
            self._explore_deep(table, depth_cap)
        else:
            self._explore_waves(table, depth_cap)
        # Sound `eventually` verdicts (liveness="device"): the shared
        # trim/reach pass over the harvested edge relation.
        self._run_liveness_analysis("sharded_bfs")

    def _explore_waves(self, table, depth_cap):
        """Wave-at-a-time host loop. With ``async_pipeline=True`` the
        harvest verdict (row pulls, tier probe, survivor re-pooling)
        rides the pipeline worker while the device runs the next chunk.
        The sharded pool COALESCES rows into chunks, so the loop only
        runs ahead of in-flight verdicts while the pool already holds a
        full chunk without them — the head ``G`` rows and the bucket
        choice are then invariant to tail appends, keeping the
        dispatched sequence bit-identical to the synchronous path's;
        below a full chunk the epoch barrier restores the synchronous
        composition exactly."""
        props = self._properties
        n, G, A = self._n, self._G, self._A
        pipe = self._pipe

        chunks = 0
        last_checkpoint = time.perf_counter()
        while True:
            if (
                pipe is not None
                and self._inflight_verdicts > 0
                and self._pool_count < G
            ):
                # Coalescing barrier (see docstring): in-flight harvest
                # verdicts may shape the next chunk — wait for them.
                # Keyed on verdicts, not pipe.pending(): a deferred
                # checkpoint pickle or evict absorb cannot add pool
                # rows, and draining on those would re-serialize the
                # exact work the deferral hides.
                pipe.drain()
            if not self._pool_count:
                break
            if not props:
                break
            if len(self._discoveries_fp) == len(props):
                break
            if (
                self._target_state_count is not None
                and self._target_state_count <= self._state_count
            ):
                break
            if self._preempt_event.is_set():
                # Wave-granular yield: the host pool IS the whole
                # remaining frontier here (no chunk in flight between
                # iterations) once the pending verdicts land, so the
                # checkpoint payload captures the run exactly and the
                # resume is bit-identical.
                if pipe is not None:
                    pipe.drain()
                self._preempt_payload = self.checkpoint_payload(
                    list(self._pool)
                )
                self._tracer.instant(
                    "sharded_bfs.preempted", batches=len(self._pool),
                    mode="wave",
                )
                return
            # Attribution window over the whole iteration (checkpoint +
            # pre-grow + dispatch + harvest). No early exit lives inside
            # it, so a plain with-block is exact; an exception unwinds
            # the window like any context manager.
            with self._wave_window():
                if (
                    self._checkpoint_path is not None
                    and chunks
                    and chunks % self._checkpoint_every == 0
                    and (time.perf_counter() - last_checkpoint)
                    >= self._checkpoint_min_interval
                ):
                    with self._phase("checkpoint"):
                        self._save_checkpoint_maybe_async()
                    last_checkpoint = time.perf_counter()
                chunks += 1
                B_glob = G * A
                if (self._l0_count + B_glob) > _MAX_LOAD * n * self._cap_loc:
                    table = self._grow_table(
                        table,
                        _pow2ceil(
                            int((self._l0_count + B_glob) / (_MAX_LOAD * n))
                        ),
                        defer_evict=pipe is not None,
                    )
                # Occupancy-adaptive dispatch: the host pool count is exact
                # (numpy rows), so the global chunk shrinks to n × the
                # smallest per-device ladder rung holding the pending rows —
                # a sparse frontier expands an n×bucket grid, not n×F_loc.
                # _pool_take's round-robin interleave then gives every shard a
                # dense live-lane prefix at that width. (Async: after the
                # coalescing barrier above, this count either matches the
                # synchronous path's exactly, or is >= G with it — same
                # bucket either way.)
                got = min(self._pool_count, G)
                width = G
                bucket = None
                if len(self._buckets) > 1:
                    bucket = bucket_for(
                        self._buckets, max(1, -(-got // n))
                    )
                    width = n * bucket
                    self._wi.bucket.set(bucket)
                    self._wi.bucket_dispatch(bucket)
                    self._wi.compaction.set(got / width)
                    self._wi.frontier_fill.set(got / G)
                chunk = self._pool_take(width)
                dev = self._put_chunk(chunk)

                if pipe is None:
                    table = self._wave_sync(
                        table, chunk, dev, depth_cap, chunks, width,
                        bucket, got,
                    )
                else:
                    # Bounded pending-verdict lane set.
                    pipe.throttle()
                    table = self._wave_async(
                        table, dev, depth_cap, chunks, width, bucket, got,
                    )
                if self.warmup_seconds is None:
                    self.warmup_seconds = time.perf_counter() - self._t_start
                    self._wi.warmup.set(self.warmup_seconds)
                # Re-ingest fresh rows for the next chunks.
                del dev
        if pipe is not None:
            # Run-end epoch barrier: counters and the parent-fp log must
            # be settled before the audit and the done flag.
            pipe.drain()
        self._audit_table(table)

    def _apply_wave_stats(self, wave, chunk=None):
        """First-attempt device bookkeeping shared by the sync and async
        wave paths (a growth retry re-expands the same chunk, so this
        runs once per wave): generated/depth counters, discovery
        fingerprints, and the visitor callback. ONE site on purpose —
        the bit-identical guarantee depends on both paths applying the
        same stats the same way. Returns the wave's generated count."""
        props = self._properties
        n = self._n
        generated = int(self._pull(wave["generated"]).sum())
        self._state_count += generated
        self._max_depth = max(
            self._max_depth, int(self._pull(wave["max_depth"]).max())
        )
        if props:
            hit = self._pull(wave["prop_hit"])
            phi = self._pull(wave["prop_hi"])
            plo = self._pull(wave["prop_lo"])
            for i, p in enumerate(props):
                if p.name in self._discoveries_fp:
                    continue
                for d in range(n):
                    if hit[d, i]:
                        self._discoveries_fp[p.name] = fp_to_int(
                            phi[d, i], plo[d, i]
                        )
                        break
        if chunk is not None and self._visitor is not None:
            self._visit_chunk(chunk)
        return generated

    def _wave_sync(self, table, chunk, dev, depth_cap, chunks, width,
                   bucket, got):
        """One wave's synchronous dispatch + harvest (the pre-async
        body, factored out unchanged)."""
        attempt = 0
        wave_generated = 0
        wave_new = 0
        self._wave_stale = 0
        with self._tracer.span(
            "sharded_bfs.wave", wave=chunks
        ) as sp, device_step_annotation("sharded_bfs.wave", chunks):
            while True:
                wave = self._call_wave(table, dev, depth_cap)
                table = wave["table"]
                if attempt == 0:
                    wave_generated = self._apply_wave_stats(wave, chunk)
                if self._cov is not None:
                    # Mesh-summed coverage vector; a growth retry
                    # re-expands the same chunk, so only the
                    # fresh-based slices accumulate then.
                    self._cov.consume_device(
                        np.asarray(
                            self._pull(wave["cov"])
                        ).sum(axis=0),
                        self._cov_layout,
                        first_attempt=(attempt == 0),
                        max_depth=self._max_depth,
                    )
                wave_new += self._harvest(wave)
                self._harvest_liveness(wave)
                if not int(self._pull(wave["overflow"]).sum()):
                    break
                if self._max_cap_loc is not None and attempt >= 8:
                    # Pathological key skew: one shard overflows even
                    # freshly evicted — a configuration error, not a
                    # loop to spin in.
                    raise RuntimeError(
                        "a single wave's routed keys overflow one "
                        "budget-capped shard after repeated "
                        "evictions; raise hbm_budget_mib or shrink "
                        "frontier_per_device"
                    )
                table = self._grow_table(table, self._cap_loc * 2)
                attempt += 1
            self._record_wave_metrics(
                sp,
                width,
                wave_generated,
                wave_new,
                bucket=bucket,
                compaction_ratio=(got / width if bucket else None),
                live_lanes=got,
            )
            if self._cov is not None:
                self._cov.emit_wave_span()
        return table

    def _wave_async(self, table, dev, depth_cap, chunks, width, bucket,
                    got):
        """One wave's async dispatch (checker thread): device stats,
        discoveries, growth retries — everything the next dispatch
        decision depends on — while each attempt's harvest verdict is
        submitted to the pipeline worker BEFORE any growth/eviction
        that follows it (the tier must see probes and evictions in the
        synchronous order; see TpuBfsChecker._consume_wave_async)."""
        attempt = 0
        ctx = {"wave_new": 0, "stale": 0, "generated": 0}
        with device_step_annotation("sharded_bfs.wave", chunks):
            while True:
                wave = self._call_wave(table, dev, depth_cap)
                table = wave["table"]
                if attempt == 0:
                    ctx["generated"] = self._apply_wave_stats(wave)
                if self._cov is not None:
                    self._cov.consume_device(
                        np.asarray(self._pull(wave["cov"])).sum(axis=0),
                        self._cov_layout,
                        first_attempt=(attempt == 0),
                        max_depth=self._max_depth,
                    )
                n_new = self._pull(wave["n_new"])
                total = int(n_new.sum())
                self._l0_count += total
                final = not int(self._pull(wave["overflow"]).sum())
                # Only jobs that can grow the pool hold up the
                # coalescing barrier (see _explore_waves); count this
                # one in BEFORE it is queued — incrementing after
                # submit could let the job's decrement land first and
                # the barrier miss a genuinely pending verdict.
                with self._pool_lock:
                    self._inflight_verdicts += 1
                try:
                    # Point-in-time captures: the live l0/capacity/depth
                    # fields may describe a later wave by verdict time.
                    self._pipe.submit(
                        lambda w=wave, nn=n_new, t=total, f=final,
                        warm=self.warmup_seconds is not None,
                        st=(
                            self._l0_count,
                            self._n * self._cap_loc,
                            self._max_depth,
                        ),
                        cm=self._last_comms:
                            self._harvest_verdict(
                                ctx, w, nn, t, f, chunks, width, bucket,
                                got, warm, st, cm,
                            )
                    )
                except BaseException:
                    # A poisoned submit never enqueues the job (whose
                    # finally would decrement) — rebalance here.
                    with self._pool_lock:
                        self._inflight_verdicts -= 1
                    raise
                if final:
                    if self._cov is not None:
                        self._cov.emit_wave_span()
                    return table
                if self._max_cap_loc is not None and attempt >= 8:
                    raise RuntimeError(
                        "a single wave's routed keys overflow one "
                        "budget-capped shard after repeated "
                        "evictions; raise hbm_budget_mib or shrink "
                        "frontier_per_device"
                    )
                table = self._grow_table(
                    table, self._cap_loc * 2, defer_evict=True
                )
                attempt += 1

    def _harvest_verdict(self, ctx, wave, n_new, total, final, wave_no,
                         width, bucket, got, warm, state, comms=None):
        """Pipeline-worker half of a sharded wave: pulls the compacted
        fresh rows, probes the shard tiers (exact here — every eviction
        is applied on this thread, in submission order), logs the
        survivors, and re-pools them at the tail. The final attempt
        emits the ``sharded_bfs.wave`` span + telemetry the monitor's
        estimator consumes."""
        def verdict():
            # Edge rows absorb even on zero-fresh waves (cycle-closing
            # edges target already-visited states).
            self._harvest_liveness(wave)
            if not total:
                return
            # _tier_active() inside _harvest_rows is exact HERE: every
            # eviction is applied on this same thread, in submission
            # order (the merge fence).
            survivors, n_stale = self._harvest_rows(
                wave, n_new, overlapped=True
            )
            ctx["stale"] += n_stale
            ctx["wave_new"] += survivors

        try:
            if not final:
                verdict()
                return
            # Covers the HOST VERDICT only (the device half overlaps
            # later waves) — flagged so trace readers don't compare its
            # dur against sync wave walls.
            with self._tracer.span(
                "sharded_bfs.wave", wave=wave_no, async_verdict=True
            ) as sp:
                verdict()
                self._record_wave_metrics(
                    sp, width, ctx["generated"], ctx["wave_new"],
                    bucket=bucket,
                    compaction_ratio=(got / width if bucket else None),
                    live_lanes=got, stale=ctx["stale"], warm=warm,
                    state=state, comms=comms,
                )
        finally:
            # Decrement even on a verdict error: the barrier predicate
            # must not wedge the checker on a job that will never
            # append (the error itself surfaces via drain/submit).
            with self._pool_lock:
                self._inflight_verdicts -= 1

    def _save_checkpoint_maybe_async(self, batches=None):
        """Checkpoint at an epoch boundary: payload built synchronously
        after the barrier; in async mode the pickle + rename ride the
        pipeline worker (see TpuBfsChecker._save_checkpoint_maybe_async
        for why that is safe). ``batches`` overrides the wave-mode host
        pool (the deep path passes ring exports); the pool itself is
        snapshotted only AFTER the barrier — in-flight verdicts append
        survivor rows during the drain."""
        if self._pipe is None:
            self.save_checkpoint(
                self._checkpoint_path,
                batches if batches is not None else self._pool,
            )
            return
        self._pipe.drain()
        payload = self.checkpoint_payload(
            list(batches) if batches is not None else list(self._pool)
        )
        path = self._checkpoint_path
        self._pipe.submit(lambda: self._checkpoint_write(path, payload))

    def _aot_disk_binding(self):
        """The disk AOT binding, built on first use (every trace-relevant
        attribute is set by the first wave). The signature mirrors the
        single-device checker's ``_aot_signature`` for the knobs the
        sharded trace closes over — backend, topology, model digest,
        properties, capacities, ladder, sieve, liveness, coverage — so a
        config drift misses instead of loading the wrong executable."""
        if self._aot_store_arg is None:
            return None
        if self._aot_disk is None:
            from ..checker.tpu import packed_model_digest
            from ..storage.persist import AotDiskStore

            store = (
                self._aot_store_arg
                if isinstance(self._aot_store_arg, AotDiskStore)
                else AotDiskStore(self._aot_store_arg)
            )
            sig = (
                "sharded_wave",
                jax.default_backend(),
                jax.process_count(),
                self._n,
                packed_model_digest(self._model, self._A),
                tuple(
                    (p.name, str(p.expectation)) for p in self._properties
                ),
                self._F_loc,
                self._cap_loc,
                tuple(self._buckets),
                bool(self._sieve),
                self._sieve_slots if self._sieve else None,
                self._sieve_bits if self._sieve else None,
                self._live_enabled,
                self._cov is not None,
                self._fleet_on,
            )
            self._aot_disk = store.binding(
                f"sharded:{type(self._model).__name__}", sig,
                registry=self._registry,
            )
        return self._aot_disk

    def _call_wave(self, table, dev, depth_cap):
        """Wave through an AOT-compiled executable (keyed by local table
        capacity): a mid-run compile (table growth changes the shape) is
        measured into ``warmup_seconds`` instead of the steady-state
        window — mirroring ``TpuBfsChecker._call_wave``. During the
        pre-first-result window ``warmup_seconds`` is None and the
        caller's own stamp covers the compile."""
        # Injection seam (utils/faults.py): pre-dispatch, like the
        # single-device checker's — no per-wave state mutated yet.
        fault_point("device.wave")
        args = (
            table,
            dev["states"],
            dev["hi"],
            dev["lo"],
            dev["ebits"],
            dev["depth"],
            dev["mask"],
            jnp.asarray(depth_cap, jnp.int32),
        )
        if self._sieve:
            args = args + self._sieve_dev
        key = (table.shape[0], dev["hi"].shape[0])
        exe = self._wave_exec.get(key)
        if exe is not None:
            disk = self._aot_disk_binding()
            if disk is not None:
                # Warm-memory / cold-disk backfill, same as the solo
                # checker's wave site.
                disk.ensure("wave", key, exe)
        if exe is None:
            disk = self._aot_disk_binding()
            if disk is not None:
                # Disk tier (warm-start plane): a fenced hit skips the
                # compile phase entirely — cross-process sharded runs
                # record zero wave compiles.
                exe = disk.load("wave", key)
                if exe is not None:
                    self._wave_exec[key] = exe
        if exe is None:
            t0 = time.perf_counter()
            # AOT-cache miss: the attribution engine's compile-detection
            # site (the hit path never enters this branch).
            with self._phase("compile"):
                exe = self._jit_wave.lower(*args).compile()
            self._wave_exec[key] = exe
            if self.warmup_seconds is not None:
                self.warmup_seconds += time.perf_counter() - t0
            disk = self._aot_disk_binding()
            if disk is not None:
                disk.save("wave", key, exe)
        if self._attr is None:
            out = exe(*args)
        else:
            with self._attr.phase("device"):
                out = exe(*args)
                self._attr.fence(out)
        if self._sieve:
            # The sieve operands are donated: rebind before anything can
            # touch the stale references.
            self._sieve_dev = (out["sieve_cache"], out["sieve_bloom"])
        args = self._consume_comms(
            out["comms"], dev["hi"].shape[0] // self._n * self._A
        )
        if self._fleet_on:
            # Mutates the stashed span-args dict in place, so the async
            # path's captured ``self._last_comms`` reference carries the
            # fleet columns with no extra plumbing.
            args.update(self._consume_fleet(out["fleet"]))
        return out

    def _consume_comms(self, comms, m):
        """Host accounting for one dispatch's mesh-summed comms vector
        (layout: ``[sieve_probes, killed, bloom_probes, bloom_hits,
        bloom_fps, lanes_shipped, rung one-hot...]`` per shard; ``m`` is
        the per-device candidate-lane width, which fixes the rung
        ladder). Returns (and stashes) the span-args dict the wave span
        rides."""
        per = np.asarray(self._pull(comms), np.int64)  # (n, vec)
        self._last_comms_per = per
        c = per.sum(axis=0)
        args = self._ci.record(
            probes=int(c[0]),
            killed=int(c[1]),
            bloom_probes=int(c[2]),
            bloom_hits=int(c[3]),
            bloom_fps=int(c[4]),
            lanes=int(c[5]),
        )
        rungs = self._comm_rungs(m) if self._sieve else [m]
        for i, width in enumerate(rungs[: max(0, len(c) - 6)]):
            cnt = int(c[6 + i])
            if cnt:
                self._ci.rung_dispatch(width, cnt)
        self._last_comms = args
        return args

    def _consume_fleet(self, fleet_dev, waves=1):
        """Folds one dispatch's per-shard skew rows — device counters
        (``_wave_core``'s ``fleet`` vector), the per-shard columns of the
        comms exchange already pulled by ``_consume_comms``, and the host
        tier walls accumulated per shard since the last fold — into the
        ``fleet.*`` family. Returns the ``fleet_*`` span args."""
        per = np.asarray(self._pull(fleet_dev), np.float64)
        if per.ndim == 1:
            per = per[None]
        n = self._n
        with self._fleet_lock:
            probe_s = self._fleet_probe_s
            evict_s = self._fleet_evict_s
            evict_b = self._fleet_evict_bytes
            self._fleet_probe_s = [0.0] * n
            self._fleet_evict_s = [0.0] * n
            self._fleet_evict_bytes = [0] * n
        rows = {
            "live_lanes": per[:, 0],
            "generated": per[:, 1],
            "fresh": per[:, 2],
            "insert_load": per[:, 3],
            "overflow": per[:, 4],
            "probe_ms": np.asarray(probe_s) * 1e3,
            "evict_ms": np.asarray(evict_s) * 1e3,
            "evict_bytes": np.asarray(evict_b, np.float64),
        }
        cm = self._last_comms_per
        if cm is not None and cm.shape[0] == n and cm.shape[1] >= 3:
            rows["sieve_hits"] = cm[:, 1].astype(np.float64)
            rows["routed"] = cm[:, 2].astype(np.float64)
        return self._fi.record_wave(rows, waves=waves)

    # -- deep-drain host loop ---------------------------------------------

    def _new_pool(self):
        W = self._n * self._PCl
        return jax.jit(
            lambda: ring_rows(self._model, W), out_shardings=self._shard
        )()

    def _new_heads(self):
        return jax.jit(
            lambda: jnp.zeros((self._n,), jnp.int32),
            out_shardings=self._shard,
        )()

    def _feed_rings(self, pool, head, count, ring_est):
        """Moves host-pool rows into the device rings, growing them when
        the next global chunk might not fit. Returns updated state."""
        G = self._G
        while self._pool_count:
            if ring_est + self._F_loc > self._PCl:
                # The host bound overcounts (F_loc per chunk regardless of
                # occupancy); refresh it from the device before paying for
                # a ring doubling and its retrace.
                ring_est = int(self._pull(count).max())
                if ring_est + self._F_loc > self._PCl:
                    pool, head, count = self._grow_rings(pool, head, count)
            chunk = self._pool_take(G)
            dev = self._put_chunk(chunk)
            out = self._jit_ring_push(pool, head, count, dev)
            pool, count = out["pool"], out["count"]
            ring_est += self._F_loc
        return pool, head, count, ring_est

    def _grow_rings(self, pool, head, count):
        """Doubles every device's ring (local export + re-push — rows never
        change device, so growth needs no communication)."""
        exported = self._jit_ring_export(pool, head, count)
        self._PCl *= 2
        pool = self._new_pool()
        head = self._new_heads()
        out = self._jit_ring_push(pool, head, self._new_heads(), exported)
        return out["pool"], head, out["count"]

    def _explore_deep(self, table, depth_cap):
        props = self._properties
        if not props:
            return
        n, G, A = self._n, self._G, self._A
        pool = self._new_pool()
        head = self._new_heads()
        count = self._new_heads()
        ring_est = 0  # conservative host bound on the fullest ring
        drains = 0
        compiled = False
        last_checkpoint = time.perf_counter()
        while True:
            if len(self._discoveries_fp) == len(props):
                break
            if self._preempt_event.is_set():
                # Drain-granular yield: rings + host-pool leftovers are
                # the whole pending frontier between drains (same
                # capture as _checkpoint_rings), into an in-memory
                # payload instead of a file.
                self._preempt_payload = self.checkpoint_payload(
                    self._rings_pool_batches(pool, head, count)
                )
                self._tracer.instant(
                    "sharded_bfs.preempted", mode="drain"
                )
                return
            pool, head, count, ring_est = self._feed_rings(
                pool, head, count, ring_est
            )
            if ring_est == 0:
                break
            # Attribution window over the whole drain iteration. No
            # early exit lives inside it (unlike TpuBfsChecker's, which
            # needs the mid-loop handoff return), so a with-block is
            # exact.
            with self._wave_window("drain"):
                if (
                    self._checkpoint_path is not None
                    and drains
                    and (time.perf_counter() - last_checkpoint)
                    >= self._checkpoint_min_interval
                ):
                    with self._phase("checkpoint"):
                        self._checkpoint_rings(pool, head, count)
                    last_checkpoint = time.perf_counter()
                drains += 1
                B_glob = G * A
                if (self._l0_count + B_glob) > _MAX_LOAD * n * self._cap_loc:
                    table = self._grow_table(
                        table,
                        _pow2ceil(
                            int((self._l0_count + B_glob) / (_MAX_LOAD * n))
                        ),
                    )
                undiscovered = np.array(
                    [p.name not in self._discoveries_fp for p in props]
                )
                # Clamp: the budget rides device int32; a huge global table
                # (> 2^31 slots across the mesh) must saturate, not overflow.
                budget = jnp.int32(
                    min(
                        int(_MAX_LOAD * n * self._cap_loc) - self._l0_count,
                        (1 << 31) - 1 - G * A,
                    )
                )
                args = (
                    table,
                    pool,
                    head,
                    count,
                    jnp.asarray(undiscovered),
                    budget,
                    depth_cap,
                )
                if self._sieve:
                    args = args + self._sieve_dev
                if not compiled:
                    # AOT-compile so the first drain (which may run the whole
                    # exploration) doesn't fold into any warmup measurement.
                    with self._phase("compile"):
                        self._jit_deep_drain.lower(*args).compile()
                    compiled = True
                    if self.warmup_seconds is None:
                        self.warmup_seconds = (
                            time.perf_counter() - self._t_start
                        )
                        self._wi.warmup.set(self.warmup_seconds)
                drain_span = self._tracer.span("sharded_bfs.drain", drain=drains)
                with drain_span, device_step_annotation(
                    "sharded_bfs.drain", drains
                ):
                    with self._phase("device"):
                        res = self._jit_deep_drain(*args)
                        if self._attr is not None:
                            self._attr.fence(res)
                    dstats = self._pull(res["drain_stats"])  # (n, 10)
                    drain_generated = int(dstats[:, 1].sum())
                    drain_new = int(dstats[:, 2].sum())
                    self._state_count += drain_generated
                    self._unique_count += drain_new
                    # Drains only run tier-empty: every fresh is L0-resident.
                    self._l0_count += drain_new
                    self._max_depth = max(
                        self._max_depth, int(dstats[:, 3].max())
                    )
                    # Aggregate span per drain (per-wave host exits are the
                    # cost the drain amortizes away); the final unconsumed
                    # wave is accounted by _consume_final below.
                    self._wi.drains.inc()
                    self._wi.waves.inc(int(dstats[:, 4].max()))
                    comms_extra = self._consume_comms(
                        res["comms_acc"], self._F_loc * self._A
                    )
                    if self._fleet_on:
                        comms_extra.update(
                            self._consume_fleet(
                                res["fleet_acc"],
                                waves=int(dstats[:, 4].max()),
                            )
                        )
                    self._wi.record(
                        drain_span,
                        frontier=self._G,
                        generated=drain_generated,
                        n_new=drain_new,
                        occupancy=self._l0_count / (self._n * self._cap_loc),
                        capacity=self._n * self._cap_loc,
                        max_depth=self._max_depth,
                        count_wave=False,
                        observe=False,
                        waves=int(dstats[:, 4].max()),
                        # Live pending states across all rings — the monitor's
                        # progress fit reads this, not the capacity `frontier`.
                        ring_count=int(dstats[:, 5].sum()),
                        **comms_extra,
                    )
                pool, head, count = res["pool"], res["head"], res["count"]
                if self._sieve:
                    fin = res["final"]
                    self._sieve_dev = (
                        fin["sieve_cache"], fin["sieve_bloom"]
                    )
                ring_est = int(dstats[:, 5].max())
                if self._cov is not None:
                    # Every drain wave (final included — see
                    # _deep_drain_local's cov_acc note), mesh-summed.
                    self._cov.consume_device(
                        np.asarray(
                            self._pull(res["cov_acc"])
                        ).sum(axis=0),
                        self._cov_layout,
                        max_depth=self._max_depth,
                    )
                # The whole drain's parent-fp stream: one (n, 6, Ll) transfer,
                # sliced per device by its log_n.
                max_log = int(dstats[:, 0].max())
                if max_log:
                    pack = self._pull(res["log_pack"][:, :, :max_log])
                    for d in range(n):
                        ln = int(dstats[d, 0])
                        if ln:
                            self._wave_log.append(
                                (
                                    fp64_pairs(pack[d, 0, :ln], pack[d, 1, :ln]),
                                    fp64_pairs(pack[d, 2, :ln], pack[d, 3, :ln]),
                                )
                            )
                            if self._symmetry_enabled:
                                self._key_log.append(
                                    fp64_pairs(pack[d, 4, :ln], pack[d, 5, :ln])
                                )
                with self._tracer.span("sharded_bfs.wave", drain=drains) as sp:
                    table, pool, head, count, ring_est = self._consume_final(
                        res, dstats, table, pool, head, count, ring_est,
                        depth_cap, span=sp,
                    )
        self._audit_table(table)

    def _consume_final(
        self, res, dstats, table, pool, head, count, ring_est, depth_cap,
        span=None,
    ):
        """Applies the drain's final (unconsumed) wave host-side: counters,
        discoveries, parent-fp log, ring push of the exchanged rows, and
        the table-growth overflow retry. ``span`` (a wave span covering
        this consume) gets the per-wave args the monitor reads — without
        it the final wave's uniques are invisible to the progress
        estimator and SSE stream (registry counters alone don't stream)."""
        props = self._properties
        n = self._n
        final = res["final"]
        table = final["table"]
        self._state_count += int(dstats[:, 7].sum())
        self._max_depth = max(self._max_depth, int(dstats[:, 9].max()))
        if props:
            hit = self._pull(res["prop_hit"])
            phi = self._pull(res["prop_hi"])
            plo = self._pull(res["prop_lo"])
            for i, p in enumerate(props):
                if p.name in self._discoveries_fp:
                    continue
                for d in range(n):
                    if hit[d, i]:
                        self._discoveries_fp[p.name] = fp_to_int(
                            phi[d, i], plo[d, i]
                        )
                        break
        # Log + count the final wave's fresh rows (generator side).
        n_new = dstats[:, 6]
        total_new = int(n_new.sum())
        self._unique_count += total_new
        self._l0_count += total_new
        self._wi.unique.inc(total_new)
        self._wi.generated.inc(int(dstats[:, 7].sum()))
        self._wi.wave_new.observe(total_new)
        if total_new:
            B = self._F_loc * self._A
            hi = self._pull(final["new_hi"]).reshape(n, B)
            lo = self._pull(final["new_lo"]).reshape(n, B)
            phi_ = self._pull(final["parent_hi"]).reshape(n, B)
            plo_ = self._pull(final["parent_lo"]).reshape(n, B)
            sel = np.zeros((n, B), bool)
            for d in range(n):
                sel[d, : int(n_new[d])] = True
            self._wave_log.append(
                (fp64_pairs(hi[sel], lo[sel]), fp64_pairs(phi_[sel], plo_[sel]))
            )
            if self._symmetry_enabled:
                khi = self._pull(final["new_khi"]).reshape(n, B)
                klo = self._pull(final["new_klo"]).reshape(n, B)
                self._key_log.append(fp64_pairs(khi[sel], klo[sel]))
            # Push the exchanged rows into the rings (device-side; the
            # exchange already balanced them round-robin).
            recv_per_dev = final["recv_mask"].shape[0] // n
            # Grow until the received rows provably fit: recv_per_dev is
            # n*ceil(B/n) and can exceed a single doubling of a small ring
            # (ring_push would silently wrap and overwrite queued states).
            while ring_est + recv_per_dev > self._PCl:
                ring_est = int(self._pull(count).max())
                if ring_est + recv_per_dev <= self._PCl:
                    break
                pool, head, count = self._grow_rings(pool, head, count)
            rows = dict(final["recv"])
            rows["mask"] = final["recv_mask"]
            out = self._jit_ring_push(pool, head, count, rows)
            pool, count = out["pool"], out["count"]
            ring_est += recv_per_dev
        # Overflow retry: grow the table and re-expand the saved frontier
        # through the wave path (fresh rows land in the host pool).
        retry_new = 0
        if int(dstats[:, 8].sum()):
            fr = res["frontier"]
            while True:
                table = self._grow_table(table, self._cap_loc * 2)
                # Through the AOT cache: the grown-shape compile is
                # measured into warmup, and the executable is shared with
                # the wave path.
                wave = self._call_wave(table, fr, depth_cap)
                table = wave["table"]
                if self._cov is not None:
                    # Retry of the drain's final frontier: its eval-based
                    # slices already rode cov_acc; only the newly-claimed
                    # fresh lanes accumulate.
                    self._cov.consume_device(
                        np.asarray(self._pull(wave["cov"])).sum(axis=0),
                        self._cov_layout,
                        first_attempt=False,
                    )
                harvested = self._harvest(wave)
                self._wi.unique.inc(harvested)
                retry_new += harvested
                if not int(self._pull(wave["overflow"]).sum()):
                    break
        if span is not None:
            gen = int(dstats[:, 7].sum())
            nn = total_new + retry_new
            span.set(
                frontier=self._G,
                generated=gen,
                new_unique=nn,
                # Clamped: the overflow retry's harvest rides nn but its
                # regeneration is already inside gen — a skewed split must
                # not stream an impossible negative rate.
                dedup_hit_rate=(max(0.0, (gen - nn) / gen) if gen else 0.0),
                occupancy=self._l0_count / (self._n * self._cap_loc),
                max_depth=self._max_depth,
                # The drain span already tallied this wave; live pending
                # (ring residue after the push) rides for the monitor's
                # frontier fit.
                waves=0,
                live_lanes=ring_est,
            )
        if self._cov is not None:
            self._cov.emit_wave_span()
        return table, pool, head, count, ring_est

    def _rings_pool_batches(self, pool, head, count):
        """The whole pending frontier in deep mode, as host row-batches:
        any host-pool leftovers plus the rings exported into one batch
        (the shape ``save_checkpoint``/``checkpoint_payload`` take)."""
        exported = self._jit_ring_export(pool, head, count)
        mask = self._pull(exported["mask"])
        batch = {
            k: (
                jax.tree_util.tree_map(lambda x: self._pull(x)[mask], v)
                if k == "states"
                else self._pull(v)[mask]
            )
            for k, v in exported.items()
            if k != "mask"
        }
        return list(self._pool) + [batch]

    def _checkpoint_rings(self, pool, head, count):
        """Deep-mode checkpoint: exports the rings into one host row-batch
        and saves it alongside any host-pool leftovers. Async mode
        defers the pickle + rename to the pipeline worker, same as the
        wave path (deep drains carry no verdicts, so the barrier is
        instant)."""
        self._save_checkpoint_maybe_async(
            self._rings_pool_batches(pool, head, count)
        )

    def _seed(self):
        """Fingerprints + dedup-inserts the initial states; returns the
        sharded visited table and fills the host pool."""
        n, G = self._n, self._G
        model = self._model
        init = model.packed_init_states()
        n0 = jax.tree_util.tree_leaves(init)[0].shape[0]
        width = max(G, n * _pow2ceil((n0 + n - 1) // n))

        def pad0(x):
            return np.pad(
                np.asarray(x), [(0, width - n0)] + [(0, 0)] * (x.ndim - 1)
            )

        init_np = jax.tree_util.tree_map(pad0, init)
        hi, lo = (np.asarray(a) for a in self._jit_fp_batch(init_np))
        if self._symmetry_enabled:
            khi, klo = (np.asarray(a) for a in self._jit_key_batch(init_np))
        else:
            khi, klo = hi, lo
        in_range = np.arange(width) < n0
        bound = np.asarray(
            jax.jit(jax.vmap(model.packed_within_boundary))(init_np)
        )
        valid = in_range & bound

        table = self._new_table()
        while True:
            out = self._jit_insert(
                table,
                *(self._put_sharded(a) for a in (khi, klo, valid)),
            )
            if not int(self._pull(out["overflow"]).sum()):
                break
            self._cap_loc *= 2
            table = self._new_table()
        table = out["table"]
        fresh = self._pull(out["fresh"])
        self._state_count = int(valid.sum())
        self._unique_count = int(fresh.sum())
        self._l0_count = self._unique_count
        # Seed the cumulative counters too (init states skip the waves).
        self._wi.generated.inc(self._state_count)
        self._wi.unique.inc(self._unique_count)
        if self._cov is not None:
            self._cov.record_seed(self._unique_count)
        child64 = fp64_pairs(hi, lo)
        self._wave_log.append((child64[fresh], np.zeros((fresh.sum(),), np.uint64)))
        if self._symmetry_enabled:
            self._key_log.append(fp64_pairs(khi, klo)[valid])
        if self._live_enabled:
            # Analysis roots: condition-false VALID init states (the
            # only legal counterexample starting points).
            from ..checker.device_liveness import seed_root_mask

            root_mask = np.asarray(
                jax.jit(
                    lambda s, v: seed_root_mask(
                        self._conditions, self._ebit, s, v
                    )
                )(init_np, jnp.asarray(valid))
            )
            self._live_store.add_roots(child64[valid], root_mask[valid])

        self._pool_append(
            {
                "states": jax.tree_util.tree_map(lambda x: x[fresh], init_np),
                "hi": hi[fresh],
                "lo": lo[fresh],
                "ebits": np.full((int(fresh.sum()),), self._ebits0, np.uint32),
                "depth": np.ones((int(fresh.sum()),), np.int32),
            }
        )
        return table

    # -- checkpoint/resume (parity with TpuBfsChecker; SURVEY §5) ----------

    def save_checkpoint(self, path, pool) -> None:
        """Atomically serializes counters, discoveries, the parent-pointer
        map, and the host frontier pool. The visited set is not stored —
        it is exactly the parent map's keys, and the per-shard tables are
        rebuilt from them on resume (keys re-route by ``hi % n``, so a
        checkpoint restores onto a mesh of any size).

        Worker-internal (called between chunks, when no chunk is in
        flight): the explicit ``pool`` argument mirrors ``TpuBfsChecker``'s
        queue parameter — calling this from another thread mid-run would
        race the worker's pool mutation and could snapshot an in-flight
        chunk out of existence."""
        payload = self.checkpoint_payload(pool)
        # Multi-controller: every process builds the identical payload;
        # exactly one writes the file.
        if jax.process_index() == 0:
            atomic_pickle(path, payload)

    def checkpoint_payload(self, pool) -> dict:
        """The checkpoint as an in-memory payload dict (the exact object
        ``save_checkpoint`` pickles); the preempt/resume path passes it
        straight to a new checker's ``resume_from=``."""
        self._ingest_wave_log()
        children, parents = self._store.export()
        payload = {
            **checkpoint_header(
                "sharded",
                self._model,
                self._A,
                self._symmetry_enabled,
                self._sym_scheme,
            ),
            "state_count": self._state_count,
            "unique_count": self._unique_count,
            "max_depth": self._max_depth,
            "discoveries": dict(self._discoveries_fp),
            "children": children,
            "parents": parents,
            "cap_loc": self._cap_loc,
            "n_shards": self._n,
            "pool": [
                jax.tree_util.tree_map(np.asarray, batch) for batch in pool
            ],
        }
        if self._symmetry_enabled:
            payload["keys"] = (
                np.concatenate(self._key_log)
                if self._key_log
                else np.zeros((0,), np.uint64)
            )
        if self._tiers and self._tier_active():
            # Out-of-core: every shard's runs + Bloom filters ride the
            # checkpoint (CRC-validated on restore); the shard tables
            # rebuild as "known keys not in any run".
            payload["storage"] = [t.export_state() for t in self._tiers]
        if self._live_enabled:
            # v3 payload extension (see checker/tpu.py): the liveness
            # edge relation + roots/terminals round-trip with the run.
            payload["liveness"] = self._live_store.export_state()
            payload["version"] = 3
        return payload

    def _restore(self, path):
        if isinstance(path, dict):
            # In-memory resume (preempt/resume): the payload dict itself.
            payload = path
        else:
            import pickle

            with open(path, "rb") as f:
                payload = pickle.load(f)
        validate_checkpoint_header(
            payload,
            "sharded",
            "single-device TpuBfs checkpoints do not carry the frontier "
            "pool this restore needs",
            self._model,
            self._A,
            self._symmetry_enabled,
            self._sym_scheme,
        )
        self._state_count = payload["state_count"]
        self._unique_count = payload["unique_count"]
        self._max_depth = payload["max_depth"]
        self._discoveries_fp = dict(payload["discoveries"])
        children = payload["children"]
        parents = payload["parents"]
        self._wave_log.append((children, parents))
        # Visited-set keys == the original fps unless symmetry was on.
        keys = children
        if self._symmetry_enabled:
            keys = payload["keys"]
            self._key_log.append(keys)
        for batch in payload["pool"]:
            self._pool_append(batch)

        # Liveness edge store must round-trip with the run (see
        # checker/tpu.py for why mode mismatches are refused).
        live_state = payload.get("liveness")
        if self._live_enabled and live_state is None:
            raise ValueError(
                "liveness='device' cannot resume a checkpoint written "
                "without it: pre-checkpoint edges were never logged, so "
                "the final verdict would be unsound"
            )
        if live_state is not None:
            if not self._live_enabled:
                raise ValueError(
                    "checkpoint carries a liveness edge store; resume "
                    "with liveness='device' (dropping it would discard "
                    "the soundness the original run paid for)"
                )
            self._live_store.load_state(live_state)

        # Out-of-core checkpoints carry per-shard run lists. Same mesh
        # width: load each store as written. Different width (elastic
        # restore): re-partition the runs' keys by owner under the
        # CURRENT mesh so per-shard host budgets stay balanced — probe
        # correctness never depended on the partitioning (union probe).
        n = self._n
        storage_state = payload.get("storage")
        if storage_state:
            if not self._tiers:
                # Restored without budget knobs: hold the runs anyway
                # (unbounded shard tables from here on, probes correct).
                from ..storage import StorageInstruments, TieredVisitedStore

                self._si = StorageInstruments(
                    "sharded_bfs", registry=self._registry
                )
                self._tiers = [
                    TieredVisitedStore(
                        instruments=self._si, shard=d, tracer=self._tracer
                    )
                    for d in range(n)
                ]
            if len(storage_state) == n:
                for t, s in zip(self._tiers, storage_state):
                    t.load_state(s)
            else:
                from ..storage.runs import FingerprintRun

                allk = [
                    FingerprintRun.from_state(r).decode_all()
                    for s in storage_state
                    for r in list(s.get("l1", [])) + list(s.get("l2", []))
                ]
                allk = np.unique(np.concatenate(allk))
                owner = ((allk >> np.uint64(32)) % np.uint64(n)).astype(
                    np.int64
                )
                for d in range(n):
                    self._tiers[d].evict(allk[owner == d])

        # Rebuild the sharded visited set by claim-inserting the L0 keys
        # (all known keys minus the tiers' runs) through the normal
        # routed insert — each key lands on its owner shard under the
        # *current* mesh, so shard count may differ from the writer's.
        if payload["n_shards"] == n:
            # Same mesh width: start at the writer's shard capacity so the
            # rebuild needs no growth rounds.
            self._cap_loc = max(self._cap_loc, payload["cap_loc"])
        insert_keys = keys
        if self._tiers and self._tier_active():
            insert_keys = keys[~self._probe_tiers(keys)]
        need = _pow2ceil(
            max(int(len(insert_keys) / (_MAX_LOAD * n)), self._cap_loc)
        )
        self._cap_loc = need
        if self._max_cap_loc is not None:
            self._cap_loc = min(self._cap_loc, self._max_cap_loc)
        table = self._new_table()
        hi = (insert_keys >> np.uint64(32)).astype(np.uint32)
        lo = (insert_keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        W = n * (1 << 13)
        if self._max_cap_loc is not None:
            # A batch must fit one freshly-evicted shard under the load
            # cap even if every key routes there.
            W = min(W, n * max(1, int(self._max_cap_loc * _MAX_LOAD) // n))
        for start in range(0, len(insert_keys), W):
            bh = hi[start : start + W]
            bl = lo[start : start + W]
            m = len(bh)
            if m < W:
                bh = np.pad(bh, (0, W - m))
                bl = np.pad(bl, (0, W - m))
            valid = np.arange(W) < m
            while True:
                out = self._jit_insert(
                    table,
                    *(self._put_sharded(a) for a in (bh, bl, valid)),
                )
                table = out["table"]
                self._l0_count += int(self._pull(out["fresh"]).sum())
                if not int(self._pull(out["overflow"]).sum()):
                    break
                table = self._grow_table(table, self._cap_loc * 2)
        return table

    def _harvest_liveness(self, wave) -> None:
        """Absorbs one wave attempt's per-shard condition-false edge
        rows into the host store (sync harvest or async verdict worker
        — FIFO keeps absorb order deterministic). Runs even on
        zero-fresh waves: cycle-closing edges point at already-visited
        states, which is exactly the n_new == 0 case."""
        if not self._live_enabled:
            return
        from ..ops.edge_store import EDGE_COLS

        ln = np.asarray(self._pull(wave["live_n"]))
        if not int(ln.sum()):
            return
        cols = {
            c: np.asarray(self._pull(wave[f"live_{c}"]))
            for c in EDGE_COLS
        }
        W = cols["phi"].shape[0] // self._n
        sel = np.zeros((self._n * W,), bool)
        for d in range(self._n):
            sel[d * W : d * W + int(ln[d])] = True
        self._live_store.absorb(**{c: cols[c][sel] for c in EDGE_COLS})

    def _harvest(self, wave):
        """Pulls each device's compacted fresh rows into the host pool;
        returns the global fresh count surviving the tier probe
        (telemetry). Out-of-core mode filters here: L0-fresh rows whose
        key lives in an evicted run are stale — never re-counted,
        re-logged, or re-pooled — so the run stays bit-identical to the
        unbounded one."""
        n_new = self._pull(wave["n_new"])
        total = int(n_new.sum())
        self._l0_count += total
        if not total:
            return total
        survivors, n_stale = self._harvest_rows(wave, n_new)
        self._wave_stale += n_stale
        return survivors

    def _harvest_rows(self, wave, n_new, overlapped=False):
        """Pull + probe + log + re-pool one wave's compacted fresh
        rows. ONE site for the sync harvest and the async verdict job —
        the key selection, stale gather, and row order must never
        diverge between them (the bit-identical guarantee).
        ``overlapped`` picks the attribution ledger (worker-thread time
        is shadowed, not serial wall). Returns
        ``(survivors, n_stale)``."""
        hi = self._pull(wave["new_hi"])
        # Per-device candidate-lane width of THIS wave (bucketed chunks
        # dispatch below G, so the width is the wave's, not the config's).
        B = hi.shape[0] // self._n
        lo = self._pull(wave["new_lo"])
        ebits = self._pull(wave["new_ebits"])
        depth = self._pull(wave["new_depth"])
        phi = self._pull(wave["parent_hi"])
        plo = self._pull(wave["parent_lo"])
        states = jax.tree_util.tree_map(self._pull, wave["new_states"])
        sel = np.zeros((self._n * B,), bool)
        for d in range(self._n):
            sel[d * B : d * B + int(n_new[d])] = True
        child64 = fp64_pairs(hi, lo)
        par64 = fp64_pairs(phi, plo)
        key64 = None
        if self._symmetry_enabled:
            key64 = fp64_pairs(
                self._pull(wave["new_khi"]), self._pull(wave["new_klo"])
            )
        idx = np.flatnonzero(sel)
        n_stale = 0
        if self._tiers and self._tier_active():
            phase = self._phase_overlapped if overlapped else self._phase
            with phase("host_probe"):
                keys = (key64 if key64 is not None else child64)[idx]
                stale = self._probe_tiers(keys)
            n_stale = int(stale.sum())
            idx = idx[~stale]
        survivors = len(idx)
        self._unique_count += survivors
        if not survivors:
            return 0, n_stale
        self._wave_log.append((child64[idx], par64[idx]))
        if self._symmetry_enabled:
            self._key_log.append(key64[idx])
        self._pool_append(
            {
                "states": jax.tree_util.tree_map(lambda x: x[idx], states),
                "hi": hi[idx],
                "lo": lo[idx],
                "ebits": ebits[idx].astype(np.uint32),
                "depth": depth[idx].astype(np.int32),
            }
        )
        return survivors, n_stale

    def _record_wave_metrics(
        self, span, frontier, generated, n_new, bucket=None,
        compaction_ratio=None, live_lanes=None, stale=None, warm=None,
        state=None, comms=None,
    ):
        """One host-visible wave's telemetry (the shared bundle does the
        recording; occupancy is the shard tables' resident load — under
        tiering the global unique count outgrows the devices).
        ``stale``/``warm``/``state`` (= (l0, total capacity, max_depth))
        are point-in-time captures the async verdict job passes in — by
        verdict time the live fields describe a later wave (a deferred
        eviction even resets l0 to 0); the synchronous path reads the
        live fields."""
        extra = {}
        if live_lanes is not None:
            # Live (pre-padding) pending rows: the monitor's frontier fit
            # reads this over the dispatch-width `frontier` when present.
            extra["live_lanes"] = live_lanes
        # Exchange ledger args (comms_lanes, sieve kill/FP counts...) ride
        # the wave span for the attribution report and gap_report. The
        # async verdict passes its capture; the sync path reads the last
        # dispatch's (growth retries overwrite — last attempt's is the
        # one whose rows this span's n_new describes).
        cm = comms if comms is not None else self._last_comms
        if cm:
            extra.update(cm)
        if state is not None:
            l0, capacity, depth = state
        else:
            l0, capacity, depth = (
                self._l0_count, self._n * self._cap_loc, self._max_depth
            )
        if self._si is not None:
            self._si.set_l0(l0)
            extra["storage_stale"] = (
                stale if stale is not None else self._wave_stale
            )
            # Worker-exact: tier mutations are FIFO-ordered, so at this
            # job's position the tier state matches the synchronous
            # path's.
            extra["storage_fps"] = sum(t.total_fps for t in self._tiers)
        steady = (
            warm if warm is not None else self.warmup_seconds is not None
        )
        self._wi.record(
            span,
            frontier=frontier,
            generated=generated,
            n_new=n_new,
            occupancy=l0 / capacity,
            capacity=capacity,
            max_depth=depth,
            phase="steady" if steady else "warmup",
            bucket=bucket,
            compaction_ratio=compaction_ratio,
            **extra,
        )

    def _visit_chunk(self, chunk):
        mask = np.asarray(chunk["mask"])
        depth = np.asarray(chunk["depth"])
        hi = np.asarray(chunk["hi"])
        lo = np.asarray(chunk["lo"])
        for i in range(len(mask)):
            if mask[i] and depth[i] < self._depth_cap:
                self._visitor.visit(
                    self._model, self._reconstruct(fp_to_int(hi[i], lo[i]))
                )

    # -- path reconstruction ----------------------------------------------

    def _host_fp(self, host_state) -> int:
        hi, lo = self._jit_fp_single(self._model.pack_state(host_state))
        return fp_to_int(hi, lo)

    def _ingest_wave_log(self):
        with self._ingest_lock:
            while self._ingested < len(self._wave_log):
                children, parents = self._wave_log[self._ingested]
                self._store.insert_batch(children, parents)
                self._ingested += 1

    def _reconstruct(self, fp: int) -> Path:
        self._ingest_wave_log()
        chain = self._store.chain(fp)
        return Path.from_fingerprints(self._model, chain, fp_of=self._host_fp)

    # -- Checker surface ---------------------------------------------------

    @property
    def pipeline(self) -> str:
        """The expansion pipeline this backend dispatches. The sharded
        wave always materializes the candidate grid (the fps wave has no
        sharded counterpart yet), but the property must exist so
        bench.py's measured-policy mismatch gate is not silently inert
        for sharded legs (``getattr(checker, "pipeline", None)`` =>
        never flags)."""
        return "materialize"

    def model(self):
        return self._model

    def state_count(self) -> int:
        return max(self._state_count, self._unique_count)

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    supports_device_liveness = True

    def discoveries(self) -> Dict[str, Path]:
        out = {
            name: self._reconstruct(fp)
            for name, fp in list(self._discoveries_fp.items())
        }
        out = self._with_device_liveness(out)
        return self._with_lassos(
            out,
            self._done_event.is_set(),
            set(self._discoveries_fp) | set(self._live_paths),
        )

    def handles(self) -> List[threading.Thread]:
        handles, self._handles = self._handles, []
        return handles

    def is_done(self) -> bool:
        return self._done_event.is_set()

    def worker_error(self) -> Optional[BaseException]:
        return self._error

    def _discovery_names(self) -> List[str]:
        # Names only — the flight recorder's digest must not trigger the
        # full path reconstruction discoveries() performs.
        return list(set(self._discoveries_fp) | set(self._live_paths))

    supports_preempt = True

    def request_preempt(self) -> None:
        """Suspend at the next wave/drain boundary into an in-memory
        checkpoint payload (``preempt_payload()``); resume with
        ``resume_from=<payload>``. Same contract as
        ``TpuBfsChecker.request_preempt``."""
        self._preempt_event.set()

    def state_digest(self) -> dict:
        digest = super().state_digest()
        digest.update(
            shards=self._n,
            table_capacity_per_shard=getattr(self, "_cap_loc", None),
            frontier_per_device=self._F_loc,
            warmup_seconds=getattr(self, "warmup_seconds", None),
            checkpoint_path=self._checkpoint_path,
            preempted=self.preempted,
            wave_kernel=self._wave_kernel,
            sieve=self._sieve,
        )
        if self.wave_kernel_reason is not None:
            digest["wave_kernel_reason"] = self.wave_kernel_reason
        if self._sieve:
            digest["comm_sieve"] = {
                "cache_slots": self._sieve_slots,
                "bloom_bits": self._sieve_bits,
            }
        if self._si is not None:
            try:
                digest["storage"] = self._si.bench_stats()
            except Exception:  # noqa: BLE001 - mid-crash best effort
                digest["storage"] = None
        return digest
