// stateright_tpu explorer — minimal vanilla-JS client for the Explorer API:
//   GET  /.status               checker progress + property discoveries
//   GET  /.states/fp1/fp2/...   replay a fingerprint path, list next steps
//   POST /.runtocompletion      unblock the on-demand checker
"use strict";

const state = {
  path: [],        // fingerprints (strings) from an init state
  labels: [],      // action label per path entry
  steps: [],       // next steps at the current state
  selected: 0,
};

const $ = (id) => document.getElementById(id);

// Server strings (state reprs, action labels, property names) are untrusted
// for HTML purposes — escape everything interpolated into innerHTML.
function esc(text) {
  return String(text)
    .replaceAll("&", "&amp;").replaceAll("<", "&lt;").replaceAll(">", "&gt;")
    .replaceAll('"', "&quot;").replaceAll("'", "&#39;");
}

async function getJSON(url, opts) {
  const response = await fetch(url, opts);
  if (!response.ok) throw new Error(`${url}: ${response.status}`);
  return response.json();
}

function badge(status) {
  const symbol = { ok: "✅", witnessed: "✅", violated: "❌", pending: "⏳" }[status] || "·";
  return `<span class="badge ${status}">${symbol}</span>`;
}

async function refreshStatus() {
  try {
    const s = await getJSON("/.status");
    $("status").textContent =
      `states=${s.state_count} unique=${s.unique_state_count} ` +
      `depth=${s.max_depth}${s.done ? " (done)" : ""}`;
    const items = s.properties.map((p) => {
      let extra = "";
      // Discoveries refute "always"/"eventually" (counterexamples) and
      // witness "sometimes" (examples).
      const refutes = p.expectation === "always" || p.expectation === "eventually";
      if (p.discovery) {
        const kind = refutes ? "counterexample" : "example";
        extra = ` <a href="#" class="discovery" data-fps="${esc(p.discovery.fingerprints)}">${kind}</a>`;
      }
      const status = p.discovery ? (refutes ? "violated" : "witnessed") : "pending";
      return `<li>${badge(status)} <b>${esc(p.expectation)}</b> ${esc(p.name)}${extra}</li>`;
    });
    $("properties").innerHTML = items.join("");
    document.querySelectorAll(".discovery").forEach((a) =>
      a.addEventListener("click", (e) => {
        e.preventDefault();
        followFingerprints(a.dataset.fps.split("/"));
      }));
  } catch (err) {
    $("status").textContent = `status error: ${err.message}`;
  }
}

async function refreshSteps() {
  const url = "/.states/" + state.path.join("/");
  const view = await getJSON(url);
  state.steps = view.next_steps;
  state.selected = 0;
  $("current-state").textContent = view.state || "(choose an initial state)";
  renderPath();
  renderSteps();
  $("svg-panel").innerHTML = view.svg || "";
}

function renderPath() {
  $("path").innerHTML = state.labels
    .map((label, i) => `<li data-i="${i}">${esc(label)}</li>`)
    .join("");
  document.querySelectorAll("#path li").forEach((li) =>
    li.addEventListener("click", () => {
      const n = Number(li.dataset.i) + 1;
      state.path = state.path.slice(0, n);
      state.labels = state.labels.slice(0, n);
      refreshSteps();
    }));
}

function renderSteps() {
  $("steps").innerHTML = state.steps
    .map((step, i) => {
      const label = step.action === null ? "(init)" : step.action;
      const props = (step.properties || [])
        .map((p) => badge(p.status))
        .join("");
      const selected = i === state.selected ? " selected" : "";
      return `<li class="step${selected}" data-i="${i}">` +
        `<b>${esc(label)}</b> ${props}<pre>${esc(step.outcome)}</pre></li>`;
    })
    .join("");
  document.querySelectorAll("#steps .step").forEach((li) =>
    li.addEventListener("click", () => takeStep(Number(li.dataset.i))));
}

function takeStep(i) {
  const step = state.steps[i];
  if (!step) return;
  state.path.push(step.fingerprint);
  state.labels.push(step.action === null ? "(init)" : step.action);
  refreshSteps();
}

async function followFingerprints(fps) {
  // Walk a discovery path fingerprint by fingerprint, labeling from the
  // server's step info at each hop.
  state.path = [];
  state.labels = [];
  for (const fp of fps) {
    const view = await getJSON("/.states/" + state.path.join("/"));
    const match = view.next_steps.find((s) => s.fingerprint === fp);
    state.path.push(fp);
    state.labels.push(match ? (match.action === null ? "(init)" : match.action) : fp);
  }
  refreshSteps();
}

document.addEventListener("keydown", (e) => {
  if (e.key === "j") {
    state.selected = Math.min(state.selected + 1, state.steps.length - 1);
    renderSteps();
  } else if (e.key === "k") {
    state.selected = Math.max(state.selected - 1, 0);
    renderSteps();
  } else if (e.key === "Enter") {
    takeStep(state.selected);
  } else if (e.key === "Backspace") {
    state.path.pop();
    state.labels.pop();
    refreshSteps();
  }
});

$("run").addEventListener("click", () =>
  fetch("/.runtocompletion", { method: "POST" }).then(refreshStatus));
$("reset").addEventListener("click", () => {
  state.path = [];
  state.labels = [];
  refreshSteps();
});

refreshSteps();
refreshStatus();
setInterval(refreshStatus, 1000);
