// stateright_tpu explorer — minimal vanilla-JS client for the Explorer API:
//   GET  /.status               checker progress + property discoveries
//   GET  /.states/fp1/fp2/...   replay a fingerprint path, list next steps
//   POST /.runtocompletion      unblock the on-demand checker
"use strict";

const state = {
  path: [],        // fingerprints (strings) from an init state
  labels: [],      // action label per path entry
  steps: [],       // next steps at the current state
  selected: 0,
};

const $ = (id) => document.getElementById(id);

// Server strings (state reprs, action labels, property names) are untrusted
// for HTML purposes — escape everything interpolated into innerHTML.
function esc(text) {
  return String(text)
    .replaceAll("&", "&amp;").replaceAll("<", "&lt;").replaceAll(">", "&gt;")
    .replaceAll('"', "&quot;").replaceAll("'", "&#39;");
}

async function getJSON(url, opts) {
  const response = await fetch(url, opts);
  if (!response.ok) throw new Error(`${url}: ${response.status}`);
  return response.json();
}

function badge(status) {
  const symbol = { ok: "✅", witnessed: "✅", violated: "❌", pending: "⏳" }[status] || "·";
  return `<span class="badge ${status}">${symbol}</span>`;
}

async function refreshStatus() {
  try {
    const s = await getJSON("/.status");
    $("status").textContent =
      `states=${s.state_count} unique=${s.unique_state_count} ` +
      `depth=${s.max_depth}${s.done ? " (done)" : ""}`;
    const items = s.properties.map((p) => {
      let extra = "";
      // Discoveries refute "always"/"eventually" (counterexamples) and
      // witness "sometimes" (examples).
      const refutes = p.expectation === "always" || p.expectation === "eventually";
      if (p.discovery) {
        const kind = refutes ? "counterexample" : "example";
        extra = ` <a href="#" class="discovery" data-fps="${esc(p.discovery.fingerprints)}">${kind}</a>`;
      }
      const status = p.discovery ? (refutes ? "violated" : "witnessed") : "pending";
      return `<li>${badge(status)} <b>${esc(p.expectation)}</b> ${esc(p.name)}${extra}</li>`;
    });
    $("properties").innerHTML = items.join("");
    document.querySelectorAll(".discovery").forEach((a) =>
      a.addEventListener("click", (e) => {
        e.preventDefault();
        followFingerprints(a.dataset.fps.split("/"));
      }));
  } catch (err) {
    $("status").textContent = `status error: ${err.message}`;
  }
}

async function refreshSteps() {
  const url = "/.states/" + state.path.join("/");
  const view = await getJSON(url);
  state.steps = view.next_steps;
  state.selected = 0;
  $("current-state").textContent = view.state || "(choose an initial state)";
  renderPath();
  renderSteps();
  $("svg-panel").innerHTML = view.svg || "";
}

function renderPath() {
  $("path").innerHTML = state.labels
    .map((label, i) => `<li data-i="${i}">${esc(label)}</li>`)
    .join("");
  document.querySelectorAll("#path li").forEach((li) =>
    li.addEventListener("click", () => {
      const n = Number(li.dataset.i) + 1;
      state.path = state.path.slice(0, n);
      state.labels = state.labels.slice(0, n);
      refreshSteps();
    }));
}

function renderSteps() {
  $("steps").innerHTML = state.steps
    .map((step, i) => {
      const label = step.action === null ? "(init)" : step.action;
      const props = (step.properties || [])
        .map((p) => badge(p.status))
        .join("");
      const selected = i === state.selected ? " selected" : "";
      return `<li class="step${selected}" data-i="${i}">` +
        `<b>${esc(label)}</b> ${props}<pre>${esc(step.outcome)}</pre></li>`;
    })
    .join("");
  document.querySelectorAll("#steps .step").forEach((li) =>
    li.addEventListener("click", () => takeStep(Number(li.dataset.i))));
}

function takeStep(i) {
  const step = state.steps[i];
  if (!step) return;
  state.path.push(step.fingerprint);
  state.labels.push(step.action === null ? "(init)" : step.action);
  refreshSteps();
}

async function followFingerprints(fps) {
  // Walk a discovery path fingerprint by fingerprint, labeling from the
  // server's step info at each hop.
  state.path = [];
  state.labels = [];
  for (const fp of fps) {
    const view = await getJSON("/.states/" + state.path.join("/"));
    const match = view.next_steps.find((s) => s.fingerprint === fp);
    state.path.push(fp);
    state.labels.push(match ? (match.action === null ? "(init)" : match.action) : fp);
  }
  refreshSteps();
}

document.addEventListener("keydown", (e) => {
  if (e.key === "j") {
    state.selected = Math.min(state.selected + 1, state.steps.length - 1);
    renderSteps();
  } else if (e.key === "k") {
    state.selected = Math.max(state.selected - 1, 0);
    renderSteps();
  } else if (e.key === "Enter") {
    takeStep(state.selected);
  } else if (e.key === "Backspace") {
    state.path.pop();
    state.labels.pop();
    refreshSteps();
  }
});

$("run").addEventListener("click", () =>
  fetch("/.runtocompletion", { method: "POST" }).then(refreshStatus));
$("reset").addEventListener("click", () => {
  state.path = [];
  state.labels = [];
  refreshSteps();
});

// ---- live monitor panel ---------------------------------------------------
// Fed by the monitor endpoints the server mounts next to the Explorer API:
// /events (SSE wave/storage stream) drives the states/s sparkline, /status
// (JSON snapshot) fills depth, hash-set fill, tier bytes, and the ETA band.
// The panel stays hidden when the endpoints are absent (plain static serve).

const monitor = { points: [], max: 120, lastStatusFetch: 0, backend: null };

function fmtNum(n) {
  if (n === null || n === undefined) return "–";
  if (n >= 1e6) return (n / 1e6).toFixed(1) + "M";
  if (n >= 1e3) return (n / 1e3).toFixed(1) + "k";
  return Number(n).toFixed(n >= 10 ? 0 : 1);
}

function fmtSecs(s) {
  if (s === null || s === undefined) return "–";
  if (s < 90) return s.toFixed(0) + "s";
  if (s < 5400) return (s / 60).toFixed(1) + "m";
  return (s / 3600).toFixed(1) + "h";
}

function drawSparkline() {
  const canvas = $("monitor-sparkline");
  const ctx = canvas.getContext("2d");
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const pts = monitor.points;
  if (pts.length < 2) return;
  const peak = Math.max(...pts, 1e-9);
  ctx.beginPath();
  // Scale x to the points present (short runs fill the canvas); only a
  // full buffer scrolls at the fixed window width.
  const span = Math.max(pts.length - 1, 1);
  pts.forEach((v, i) => {
    const x = (i / span) * canvas.width;
    const y = canvas.height - 2 - (v / peak) * (canvas.height - 6);
    i === 0 ? ctx.moveTo(x, y) : ctx.lineTo(x, y);
  });
  ctx.strokeStyle = "#10394c";
  ctx.lineWidth = 1.5;
  ctx.stroke();
}

function onWaveEvent(d) {
  // Remember the live backend's span-name prefix ("tpu_bfs.drain" ->
  // "tpu_bfs"): the metrics registry is process-global, so a finished
  // earlier run's gauges must not shadow this run's in /status picks.
  if (d.name) monitor.backend = d.name.split(".")[0];
  if (d.ewma_states_per_s !== null && d.ewma_states_per_s !== undefined) {
    monitor.points.push(d.ewma_states_per_s);
    if (monitor.points.length > monitor.max) monitor.points.shift();
    $("mon-rate").textContent = fmtNum(d.ewma_states_per_s);
  }
  if (d.max_depth !== null && d.max_depth !== undefined)
    $("mon-depth").textContent = d.max_depth;
  if (d.occupancy !== null && d.occupancy !== undefined)
    $("mon-fill").textContent = (100 * d.occupancy).toFixed(1) + "%";
  if (d.eta_s_low !== null && d.eta_s_low !== undefined)
    $("mon-eta").textContent =
      fmtSecs(d.eta_s_low) + "–" + fmtSecs(d.eta_s_high);
  drawSparkline();
}

async function refreshMonitorStatus() {
  // Throttled: storage events can arrive several times per wave during
  // heavy spilling, and each full /status fetch is not free.
  const now = Date.now();
  if (now - monitor.lastStatusFetch < 1500) return;
  monitor.lastStatusFetch = now;
  try {
    const s = await getJSON("/status");
    const m = s.metrics || {};
    const pick = (suffix) => {
      // Prefer the backend the SSE stream says is live; fall back to any
      // suffix match (single-backend processes, pre-first-wave polls).
      let fallback = null;
      for (const k of Object.keys(m)) {
        if (!k.endsWith(suffix)) continue;
        if (monitor.backend && k.startsWith(monitor.backend + "."))
          return m[k];
        if (fallback === null) fallback = m[k];
      }
      return fallback;
    };
    const occ = pick(".hashset_occupancy");
    if (occ !== null) $("mon-fill").textContent = (100 * occ).toFixed(1) + "%";
    const l0 = pick(".storage.l0_resident");
    if (l0 !== null) $("mon-l0").textContent = fmtNum(l0) + " fps";
    const hostB = pick(".storage.host_bytes");
    const diskB = pick(".storage.disk_bytes");
    if (hostB !== null || diskB !== null)
      $("mon-tiers").textContent =
        fmtNum(hostB || 0) + "B / " + fmtNum(diskB || 0) + "B";
    // Pipeline attribution (attribution-mode runs only): cumulative
    // device share of wave wall — the utilization row.
    const util = m["monitor.pipeline.utilization"];
    if (util !== null && util !== undefined)
      $("mon-util").textContent = (100 * util).toFixed(1) + "%";
    // Coverage cartography (coverage-mode device runs; host engines
    // always-on): the action-coverage fraction plus the per-action bar
    // view built from the <prefix>.coverage.action_* counters.
    const acov = m["monitor.coverage.action_coverage"];
    if (acov !== null && acov !== undefined)
      $("mon-action-cov").textContent = (100 * acov).toFixed(0) + "%";
    // Swarm runs: the unique-coverage sample (distinct walk
    // fingerprints; "≥" once the fixed-capacity sample table saturated
    // — the estimate is then an honest lower bound).
    const swarmUnique = pick("swarm.unique_sample");
    if (swarmUnique !== null) {
      const sat = m["swarm.sample_saturated"];
      $("mon-swarm").textContent =
        (sat ? "≥" : "") + fmtNum(swarmUnique) + " uniq";
    }
    renderCoverageBars(m);
    const p = s.progress || {};
    if (p.max_depth !== null && p.max_depth !== undefined)
      $("mon-depth").textContent = p.max_depth;
    if (p.eta_s_low !== null && p.eta_s_low !== undefined)
      $("mon-eta").textContent =
        fmtSecs(p.eta_s_low) + "–" + fmtSecs(p.eta_s_high);
  } catch (err) {
    // monitor endpoints absent or mid-teardown; leave the panel as-is
  }
}

// ---- coverage panel -------------------------------------------------------
// Per-action fired/fresh bars from the registry snapshot in /status:
// `<prefix>.coverage.action_fired.<label>` counters (the live backend's
// prefix preferred, like every other pick). Dead actions (fired == 0)
// render flagged — the vacuity signal the panel exists for.

function renderCoverageBars(m) {
  const fired = {};
  const fresh = {};
  for (const k of Object.keys(m)) {
    const fi = k.indexOf(".coverage.action_fired.");
    const fr = k.indexOf(".coverage.action_fresh.");
    const backendOk = !monitor.backend || k.startsWith(monitor.backend + ".");
    if (fi >= 0 && backendOk)
      fired[k.slice(fi + ".coverage.action_fired.".length)] = m[k];
    if (fr >= 0 && backendOk)
      fresh[k.slice(fr + ".coverage.action_fresh.".length)] = m[k];
  }
  const labels = Object.keys(fired).sort();
  if (!labels.length) return;
  $("coverage-panel").classList.remove("hidden");
  const peak = Math.max(...labels.map((l) => fired[l]), 1);
  $("coverage-bars").innerHTML = labels
    .map((l) => {
      const f = fired[l] || 0;
      const n = fresh[l] || 0;
      const w = Math.max(1, Math.round((100 * f) / peak));
      // Percent of the PARENT fired span (CSS resolves nested % widths
      // against the parent), so the fresh fill is n/f of the fired bar.
      const wn = f ? Math.round((100 * n) / f) : 0;
      const dead = f === 0;
      return (
        `<div class="covrow${dead ? " dead" : ""}" title="fired=${f} fresh=${n}">` +
        `<span class="covlabel">${esc(l)}</span>` +
        `<span class="covbar"><span class="fired" style="width:${w}%">` +
        `<span class="fresh" style="width:${wn}%"></span></span></span>` +
        `<span class="covnum">${dead ? "DEAD" : fmtNum(f)}</span></div>`
      );
    })
    .join("");
}

// ---- fleet skew panel -----------------------------------------------------
// Per-shard load bars + skew stats + the straggler call from /fleet
// (telemetry/server.py fleet_view). Refreshes are driven by the SSE
// "fleet" events the monitor publishes per folded wave, throttled like
// /status. Panel stays hidden on single-device runs (no fleet rows).

const fleet = { lastFetch: 0 };

async function refreshFleet() {
  const now = Date.now();
  if (now - fleet.lastFetch < 1500) return;
  fleet.lastFetch = now;
  try {
    const f = await getJSON("/fleet");
    const rows = f.per_shard || [];
    if (!rows.length) return;
    $("fleet-panel").classList.remove("hidden");
    // Bar per shard on the straggler detector's cost basis: host tier
    // walls when any shard paid one, owner-side insert load otherwise.
    const anyHost = rows.some((r) => (r.probe_ms || 0) + (r.evict_ms || 0) > 0);
    const cost = (r) =>
      anyHost ? (r.probe_ms || 0) + (r.evict_ms || 0) : r.insert_load || 0;
    const peak = Math.max(...rows.map(cost), 1e-9);
    const worst = (f.stragglers || [])[0];
    $("fleet-bars").innerHTML = rows
      .map((r) => {
        const c = cost(r);
        const w = Math.max(1, Math.round((100 * c) / peak));
        const straggling = worst && worst.shard === r.shard;
        const label = `s${r.shard}` + (f.hosts > 1 ? `/h${r.host}` : "");
        return (
          `<div class="covrow${straggling ? " dead" : ""}" ` +
          `title="live=${fmtNum(r.live_lanes)} fresh=${fmtNum(r.fresh)} ` +
          `insert=${fmtNum(r.insert_load)} probe=${fmtNum(r.probe_ms)}ms">` +
          `<span class="covlabel">${esc(label)}</span>` +
          `<span class="covbar"><span class="fired" style="width:${w}%"></span></span>` +
          `<span class="covnum">${straggling ? "SLOW" : fmtNum(c)}</span></div>`
        );
      })
      .join("");
    const skew = f.skew || {};
    const parts = Object.keys(skew)
      .sort()
      .map((c) => `${esc(c)} ×${skew[c].max_over_mean.toFixed(2)}`);
    let text = parts.length ? `skew (max/mean): ${parts.join(", ")}` : "";
    if (worst && worst.persistence > 0)
      text +=
        `${text ? " — " : ""}straggler: shard ${worst.shard}` +
        ` (slowest ${(100 * worst.persistence).toFixed(0)}% of waves)`;
    $("fleet-skew").textContent = text;
  } catch (err) {
    // /fleet absent (older server) or mid-teardown; panel stays as-is
  }
}

// ---- job SLO panel --------------------------------------------------------
// Per-mode rolling latency objectives from the service's /slo endpoint
// (service/slo.py snapshot): ttfv p50/p99, the queue/compile/explore
// decomposition, burn rate vs targets. Probed once like /jobs — an
// Explorer-only serve never 404-polls for a hidden panel.

async function refreshSlo() {
  const s = await getJSON("/slo");
  const modes = s.modes || {};
  const rows = Object.keys(modes)
    .filter((m) => (modes[m].jobs || 0) > 0)
    .map((m) => {
      const v = modes[m];
      const d = v.decomposition || {};
      const p50 = (block) =>
        block && block.p50_s != null ? fmtSecs(block.p50_s) : "–";
      const burn = v.burn_rate
        ? Object.keys(v.burn_rate)
            .sort()
            .map((k) => `${esc(k)} ${v.burn_rate[k].toFixed(1)}×`)
            .join(", ")
        : "–";
      const hot = v.burn_rate &&
        Object.values(v.burn_rate).some((b) => b > 1.0);
      return (
        `<tr class="${hot ? "job-failed" : ""}">` +
        `<td>${esc(m)}</td><td>${v.jobs}</td>` +
        `<td>${p50(v.ttfv)}</td>` +
        `<td>${v.ttfv.p99_s != null ? fmtSecs(v.ttfv.p99_s) : "–"}</td>` +
        `<td>${p50(d.queue_s)}</td><td>${p50(d.compile_s)}</td>` +
        `<td>${p50(d.explore_s)}</td><td>${burn}</td></tr>`
      );
    });
  $("slo-rows").innerHTML = rows.join("");
  if (rows.length) $("slo-panel").classList.remove("hidden");
}

async function startSlo() {
  try {
    await refreshSlo();
  } catch (err) {
    return; // no /slo on this server: panel stays hidden
  }
  setInterval(() => refreshSlo().catch(() => {}), 2000);
}

function startMonitor() {
  let es;
  try {
    es = new EventSource("/events");
  } catch (err) {
    return;
  }
  let everConnected = false;
  es.addEventListener("hello", () => {
    $("monitor-panel").classList.remove("hidden");
    if (!everConnected) {
      everConnected = true;
      // Status polling only once the endpoints are known to exist —
      // a static serve must not 404-poll forever for a hidden panel.
      setInterval(refreshMonitorStatus, 2000);
    }
  });
  es.addEventListener("wave", (e) => onWaveEvent(JSON.parse(e.data)));
  es.addEventListener("storage", () => refreshMonitorStatus());
  es.addEventListener("pipeline", (e) => {
    const d = JSON.parse(e.data);
    if (d.utilization !== null && d.utilization !== undefined)
      $("mon-util").textContent = (100 * d.utilization).toFixed(1) + "%";
  });
  es.addEventListener("coverage", () => refreshMonitorStatus());
  es.addEventListener("fleet", () => refreshFleet());
  es.onerror = () => {
    // Never connected => no monitor endpoints on this server: close for
    // good, panel stays hidden. Once live, errors are transient drops —
    // leave the EventSource alone so its auto-reconnect resumes the
    // stream (the long-run case the panel exists for).
    if (!everConnected) es.close();
  };
}

// ---- job list panel -------------------------------------------------------
// Fed by the check service's /jobs endpoint (stateright_tpu.service).
// Hidden unless the serving process actually runs a CheckService — one
// probe decides, so an Explorer-only or static serve never 404-polls.

async function refreshJobs() {
  const data = await getJSON("/jobs");
  const rows = (data.jobs || []).map((j) => {
    const lat = j.latency || {};
    const unique =
      j.result && j.result.unique !== undefined ? j.result.unique : "–";
    const verdict =
      j.result === null || j.result === undefined
        ? ""
        : j.result.properties_hold
        ? " ✅"
        : " ❌";
    const cancellable = !["done", "failed", "cancelled"].includes(j.state);
    const btn = cancellable
      ? `<button class="cancel-job" data-id="${esc(j.job_id)}">✕</button>`
      : "";
    return (
      `<tr class="job-${esc(j.state)}">` +
      `<td>${esc(j.job_id)}</td><td>${esc(j.model || "")}</td>` +
      `<td>${esc(j.state)}${verdict}</td><td>${esc(unique)}</td>` +
      `<td>${lat.ttfv_s == null ? "–" : fmtSecs(lat.ttfv_s)}</td>` +
      `<td>${lat.wall_s == null ? "–" : fmtSecs(lat.wall_s)}</td>` +
      `<td>${j.preempts || 0}</td><td>${btn}</td></tr>`
    );
  });
  $("jobs-rows").innerHTML = rows.join("");
  document.querySelectorAll(".cancel-job").forEach((b) =>
    b.addEventListener("click", () =>
      fetch(`/jobs/${b.dataset.id}/cancel`, { method: "POST" })
        .then(refreshJobs)));
}

async function startJobs() {
  try {
    await refreshJobs();
  } catch (err) {
    return; // no /jobs on this server: panel stays hidden
  }
  $("jobs-panel").classList.remove("hidden");
  setInterval(() => refreshJobs().catch(() => {}), 2000);
}

refreshSteps();
refreshStatus();
setInterval(refreshStatus, 1000);
startMonitor();
startJobs();
startSlo();
