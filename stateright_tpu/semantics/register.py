"""Read/write register reference semantics.

Reference: ``Register`` at ``/root/reference/src/semantics/register.rs``.
"""

from __future__ import annotations

from .base import SequentialSpec


def Write(value):
    return ("Write", value)


READ = ("Read",)
WRITE_OK = ("WriteOk",)


def ReadOk(value):
    return ("ReadOk", value)


class Register(SequentialSpec):
    """A simple register: Write(v) -> WriteOk; Read -> ReadOk(current)."""

    def __init__(self, value):
        self.value = value

    def invoke(self, op):
        if op[0] == "Write":
            self.value = op[1]
            return WRITE_OK
        if op == READ:
            return ReadOk(self.value)
        raise ValueError(f"unknown register op: {op!r}")

    def is_valid_step(self, op, ret) -> bool:
        if op[0] == "Write" and ret == WRITE_OK:
            self.value = op[1]
            return True
        if op == READ and ret[0] == "ReadOk":
            return self.value == ret[1]
        return False

    def clone(self) -> "Register":
        return Register(self.value)

    def __stable_fields__(self):
        return ("Register", self.value)

    def __eq__(self, other):
        return isinstance(other, Register) and self.value == other.value

    def __hash__(self):
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self):
        return f"Register({self.value!r})"
