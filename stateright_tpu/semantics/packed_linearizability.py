"""Device-side linearizability: bounded-width packed histories + a traceable
serialization-search predicate.

The host ``LinearizabilityTester`` (``semantics/linearizability.py``, ported
from ``/root/reference/src/semantics/linearizability.rs:57-312``) is
recursive and pointer-heavy — infeasible to trace. SURVEY §7's "hard parts"
names the alternative implemented here: *bound op counts and precompute
serializability tables*. For register-protocol workloads every client thread
performs a statically bounded number of operations (``put_count`` Puts then
one Get), so

- the tester state packs into a fixed-width u32 vector: per thread, a
  completed-op count plus ``O`` op slots ``[kind, value, constraint[C]]``
  where ``constraint[p]`` records peer ``p``'s completed-op count at
  invocation time (the host's ``completed_map`` real-time constraint in
  dense form), with slot ``j == count`` holding the in-flight op if any;
- the Wing&Gong search becomes a *data-parallel scan over a precomputed
  interleaving table*: every program-order-respecting interleaving of the
  per-thread op streams (a multinomial — e.g. 6 for 2 threads × 2 ops),
  crossed with the 2^C choices of which in-flight ops to linearize. Each
  (interleaving, inclusion) lane replays the register semantics and the
  real-time constraints with masks; the history is linearizable iff any
  lane validates. All shapes are static, so the whole predicate fuses into
  the wave kernel — no host round trip, unlike the reference where this
  check dominates the hot loop (SURVEY §2.4).

Encoding invariants (bijective with the host tester for reachable register
histories — exact-count parity depends on it):
- ``hist[0]``: ``is_valid_history`` (1/0). Invalid histories freeze.
- thread ``c`` occupies ``1 + c*TW .. 1 + (c+1)*TW`` with
  ``TW = 1 + O*(2+C)``: count word, then op slots in program order.
- op kinds: 0 = absent, 1 = write (value = written char), 2 = read
  (value = returned char for completed reads, 0 while in flight).
- an empty ``history_by_thread`` entry on the host co-occurs with an
  in-flight op, so "thread ever invoked" is recoverable from the slots.
"""

from __future__ import annotations

from itertools import product
from typing import List, Sequence

import numpy as np

from .register import READ, ReadOk, Register, Write, WRITE_OK
from .linearizability import LinearizabilityTester


def _interleavings(C: int, O: int) -> np.ndarray:
    """All orderings of C streams × O slots that respect stream order:
    (S, M) arrays of thread ids and occurrence indexes, M = C*O."""
    M = C * O
    seqs: List[List[int]] = []

    def rec(prefix, used):
        if len(prefix) == M:
            seqs.append(list(prefix))
            return
        for t in range(C):
            if used[t] < O:
                used[t] += 1
                prefix.append(t)
                rec(prefix, used)
                prefix.pop()
                used[t] -= 1

    rec([], [0] * C)
    seq_t = np.array(seqs, np.int32)
    seq_j = np.zeros_like(seq_t)
    for s in range(seq_t.shape[0]):
        occ = [0] * C
        for pos in range(M):
            t = int(seq_t[s, pos])
            seq_j[s, pos] = occ[t]
            occ[t] += 1
    return seq_t, seq_j


class PackedRegisterLinearizability:
    """Packs ``LinearizabilityTester(Register(default))`` histories for
    ``thread_ids`` client threads with at most ``ops_per_thread`` operations
    each, and builds the traceable hooks + predicate."""

    def __init__(
        self,
        thread_ids: Sequence,
        ops_per_thread: int,
        default_value: str,
    ):
        self.thread_ids = [int(t) for t in thread_ids]
        self.C = len(self.thread_ids)
        self.O = ops_per_thread
        self.default_value = default_value
        self.TW = 1 + self.O * (2 + self.C)
        self.width = 1 + self.C * self.TW
        self._dense = {t: c for c, t in enumerate(self.thread_ids)}

    # -- host <-> packed ----------------------------------------------------

    def pack(self, tester: LinearizabilityTester) -> np.ndarray:
        C, O = self.C, self.O
        out = np.zeros((self.width,), np.uint32)
        out[0] = 1 if tester.is_valid_history else 0

        def constraint_vec(completed_map):
            vec = np.zeros((C,), np.uint32)
            for peer, last_idx in completed_map:
                vec[self._dense[int(peer)]] = last_idx + 1
            return vec

        def slot_base(c, j):
            return 1 + c * self.TW + 1 + j * (2 + C)

        for t, entries in tester.history_by_thread.items():
            c = self._dense[int(t)]
            if len(entries) > O:
                raise ValueError(
                    f"thread {t} has {len(entries)} completed ops; "
                    f"ops_per_thread={O} is too small"
                )
            out[1 + c * self.TW] = len(entries)
            for j, (completed_map, op, ret) in enumerate(entries):
                b = slot_base(c, j)
                if op[0] == "Write":
                    out[b] = 1
                    out[b + 1] = ord(op[1])
                else:  # READ; ret = ReadOk(value)
                    out[b] = 2
                    out[b + 1] = ord(ret[1])
                out[b + 2 : b + 2 + C] = constraint_vec(completed_map)
        for t, (completed_map, op) in tester.in_flight_by_thread.items():
            c = self._dense[int(t)]
            j = int(out[1 + c * self.TW])
            if j >= O:
                raise ValueError(
                    f"thread {t} in-flight op overflows ops_per_thread={O}"
                )
            b = slot_base(c, j)
            if op[0] == "Write":
                out[b] = 1
                out[b + 1] = ord(op[1])
            else:
                out[b] = 2
            out[b + 2 : b + 2 + C] = constraint_vec(completed_map)
        return out

    def unpack(self, vec: np.ndarray) -> LinearizabilityTester:
        C, O = self.C, self.O
        vec = np.asarray(vec)
        tester = LinearizabilityTester(Register(self.default_value))
        tester.is_valid_history = bool(vec[0])

        def read_slot(c, j):
            b = 1 + c * self.TW + 1 + j * (2 + C)
            kind = int(vec[b])
            value = int(vec[b + 1])
            constr = vec[b + 2 : b + 2 + C]
            completed_map = tuple(
                sorted(
                    (self.thread_ids[p], int(constr[p]) - 1)
                    for p in range(C)
                    if constr[p] > 0
                )
            )
            return kind, value, completed_map

        from ..actor.actor import Id

        for c, t in enumerate(self.thread_ids):
            tid = Id(t)
            count = int(vec[1 + c * self.TW])
            entries = []
            for j in range(count):
                kind, value, completed_map = read_slot(c, j)
                if kind == 1:
                    entries.append((completed_map, Write(chr(value)), WRITE_OK))
                else:
                    entries.append((completed_map, READ, ReadOk(chr(value))))
            in_flight = None
            if count < O:
                kind, value, completed_map = read_slot(c, count)
                if kind == 1:
                    in_flight = (completed_map, Write(chr(value)))
                elif kind == 2:
                    in_flight = (completed_map, READ)
            if entries or in_flight is not None:
                tester.history_by_thread[tid] = entries
            if in_flight is not None:
                tester.in_flight_by_thread[tid] = in_flight
        return tester

    # -- traceable structure helpers ---------------------------------------

    def _split(self, hist):
        """(valid, counts (C,), slots (C, O, 2+C)) views of the flat vector."""
        C, O = self.C, self.O
        valid = hist[0]
        body = hist[1:].reshape(C, self.TW)
        counts = body[:, 0]
        slots = body[:, 1:].reshape(C, O, 2 + C)
        return valid, counts, slots

    def _join(self, valid, counts, slots):
        import jax.numpy as jnp

        C = self.C
        body = jnp.concatenate(
            [counts[:, None], slots.reshape(C, -1)], axis=1
        )
        return jnp.concatenate([valid[None], body.reshape(-1)])

    # -- traceable recording hooks ------------------------------------------

    def on_invoke(self, hist, c, kind, value, active):
        """Records an invocation by dense thread ``c`` (traced scalar).
        Mirrors host ``on_invoke``: double-in-flight invalidates the
        history; the constraint vector snapshots peer completed counts."""
        import jax.numpy as jnp

        C, O = self.C, self.O
        valid, counts, slots = self._split(hist)
        cnt = counts[c]
        j = jnp.clip(cnt, 0, O - 1).astype(jnp.int32)
        in_flight = slots[c, j, 0] != 0
        overflow = cnt >= O
        bad = in_flight | overflow
        constr = counts.at[c].set(0)
        new_slot = jnp.concatenate(
            [
                jnp.stack([kind.astype(jnp.uint32), value.astype(jnp.uint32)]),
                constr.astype(jnp.uint32),
            ]
        )
        live = active & (valid == 1)
        apply = live & ~bad
        slots = slots.at[c, j].set(
            jnp.where(apply, new_slot, slots[c, j])
        )
        valid = jnp.where(live & bad, jnp.uint32(0), valid)
        return self._join(valid, counts, slots)

    def on_return(self, hist, c, ret_value, active):
        """Records a return for dense thread ``c``: completes the in-flight
        op (reads store the returned value); a return with no in-flight op
        invalidates the history (host ``on_return``)."""
        import jax.numpy as jnp

        C, O = self.C, self.O
        valid, counts, slots = self._split(hist)
        cnt = counts[c]
        j = jnp.clip(cnt, 0, O - 1).astype(jnp.int32)
        kind = slots[c, j, 0]
        has_inflight = (kind != 0) & (cnt < O)
        live = active & (valid == 1)
        apply = live & has_inflight
        is_read = kind == 2
        new_value = jnp.where(
            is_read, ret_value.astype(jnp.uint32), slots[c, j, 1]
        )
        slots = slots.at[c, j, 1].set(jnp.where(apply, new_value, slots[c, j, 1]))
        counts = counts.at[c].add(jnp.where(apply, jnp.uint32(1), jnp.uint32(0)))
        valid = jnp.where(live & ~has_inflight, jnp.uint32(0), valid)
        return self._join(valid, counts, slots)

    # -- the traceable predicate --------------------------------------------

    def predicate(self, real_time: bool = True):
        """Builds ``fn(hist) -> bool``: True iff a serialization exists.
        vmap over state batches; everything is static-shaped.

        ``real_time=False`` drops the recorded real-time constraints and
        decides *sequential consistency* instead (host analog:
        ``SequentialConsistencyTester`` — same search minus
        ``_violates_real_time``). The packed encoding is unchanged; the
        constraint words are simply ignored, so one packed batch can be
        audited under either criterion.

        Implementation: dynamic programming over *consumption vectors*
        instead of enumerating the multinomial × 2^C lane grid
        (``predicate_lanes``, kept for cross-checking). A node is the
        vector of per-thread consumed-op counts — the shared prefix class
        of every interleaving that consumed those ops in any order. Per
        node the DP carries a bitmask over the value universe (default +
        each slot's written/observed value): bit i set iff some
        program-order-respecting prefix reaches this node with register
        value ``U[i]``. Transitions consume thread ``t``'s next op — the
        slot index is the node's own count, so ALL indexing is static (no
        device gathers, unlike the lane grid). Real-time constraints
        depend only on the consumed-count vector, so they are exact per
        node; in-flight inclusion needs no 2^C factor because acceptance
        allows stopping at any node that consumed every COMPLETED op.
        Node count is ``(O+1)^C`` vs ``(C*O)!/(O!)^C * 2^C`` lanes — for
        3 clients × 2 ops: 27 nodes vs 720 lanes; for 4 clients: 81 vs
        40,320. Exactness is pinned against the host Wing&Gong tester on
        every reachable state (tests/test_packed_history.py) and against
        the lane grid on random histories. Reference hot spot this
        replaces: ``/root/reference/src/semantics/linearizability.rs:
        179-284`` (the recursive search the reference re-runs per state).
        """
        import jax.numpy as jnp

        C, O = self.C, self.O
        nodes = sorted(
            product(range(O + 1), repeat=C), key=lambda c: (sum(c), c)
        )
        node_idx = {c: i for i, c in enumerate(nodes)}
        default = np.uint32(ord(self.default_value))
        V = 1 + C * O  # value universe: default + one per op slot
        if V > 32:
            # The value-set bitmask is one u32 per DP node; silent bit
            # wraparound would yield wrong verdicts. (The lane grid made
            # such configs unreachable — (C*O)! lanes — so only the DP
            # can even be asked.)
            raise ValueError(
                f"packed linearizability supports at most 31 ops total "
                f"({C} clients x {O} ops = {C * O}); widen the DP value "
                "mask to u64 pairs to go further"
            )
        BITS = jnp.asarray((1 << np.arange(V)).astype(np.uint32))

        def fn(hist):
            valid, counts, slots = self._split(hist)
            U = jnp.concatenate(
                [
                    jnp.full((1,), default, jnp.uint32),
                    slots[:, :, 1].reshape(-1).astype(jnp.uint32),
                ]
            )

            def eq_bits(v):
                return jnp.where(U == v, BITS, jnp.uint32(0)).sum()

            EB = [[eq_bits(slots[t, j, 1]) for j in range(O)] for t in range(C)]
            masks = [jnp.uint32(0)] * len(nodes)
            masks[0] = eq_bits(jnp.uint32(default))
            accept = jnp.bool_(False)
            for i, c in enumerate(nodes):
                m = masks[i]
                done = jnp.bool_(True)
                for t in range(C):
                    done &= jnp.uint32(c[t]) >= counts[t]
                accept |= done & (m != 0)
                for t in range(C):
                    j = c[t]
                    if j >= O:
                        continue
                    succ = node_idx[c[:t] + (j + 1,) + c[t + 1 :]]
                    kind = slots[t, j, 0]
                    constr = slots[t, j, 2:]
                    completed = jnp.uint32(j) < counts[t]
                    inflight = (jnp.uint32(j) == counts[t]) & (kind != 0)
                    present = completed | inflight
                    cvec = jnp.asarray(np.array(c, np.uint32))
                    rt_ok = (
                        (cvec >= constr).all() if real_time
                        else jnp.bool_(True)
                    )
                    eb = EB[t][j]
                    write_m = jnp.where(m != 0, eb, jnp.uint32(0))
                    # In-flight reads generate their return: no constraint.
                    read_m = jnp.where(completed, m & eb, m)
                    m_next = jnp.where(
                        kind == 1, write_m, jnp.where(kind == 2, read_m, m)
                    )
                    contrib = jnp.where(
                        present & rt_ok, m_next, jnp.uint32(0)
                    )
                    masks[succ] = masks[succ] | contrib
            return (valid == 1) & accept

        return fn

    def predicate_lanes(self, real_time: bool = True):
        """The original lane-grid predicate (every interleaving × every
        in-flight inclusion as an independent lane) — superseded by the
        consumption-vector DP above, kept as an independent oracle for
        equivalence tests. ``real_time=False`` decides sequential
        consistency (constraint words ignored), mirroring
        ``predicate``."""
        import jax
        import jax.numpy as jnp

        C, O = self.C, self.O
        M = C * O
        seq_t, seq_j = _interleavings(C, O)
        S = seq_t.shape[0]
        masks = np.array(list(product([0, 1], repeat=C)), np.uint32)
        K = masks.shape[0]
        # The (S*K, ...) lane grid: interleaving × in-flight inclusion.
        SEQ_T = jnp.asarray(np.repeat(seq_t, K, axis=0))
        SEQ_J = jnp.asarray(np.repeat(seq_j, K, axis=0))
        MASKS = jnp.asarray(np.tile(masks, (S, 1)))
        default = np.uint32(ord(self.default_value))

        def lane(seq_t_row, seq_j_row, inc, counts, slots):
            val = jnp.uint32(default)
            ok = jnp.bool_(True)
            consumed = jnp.zeros((C,), jnp.uint32)
            for pos in range(M):  # static unroll; M is small
                t = seq_t_row[pos]
                j = seq_j_row[pos]
                kind = slots[t, j, 0]
                v = slots[t, j, 1]
                constr = slots[t, j, 2:]
                completed = j.astype(jnp.uint32) < counts[t]
                inflight = (
                    (j.astype(jnp.uint32) == counts[t])
                    & (kind != 0)
                    & (inc[t] == 1)
                )
                present = completed | inflight
                rt_ok = (
                    (consumed >= constr).all() if real_time
                    else jnp.bool_(True)
                )
                ok &= ~present | rt_ok
                # Register semantics: completed reads must observe the
                # current value; writes update it; in-flight ops generate
                # their return, so they are always valid.
                ok &= ~(present & completed & (kind == 2)) | (val == v)
                val = jnp.where(present & (kind == 1), v, val)
                consumed = consumed.at[t].add(present.astype(jnp.uint32))
            return ok

        def fn(hist):
            valid, counts, slots = self._split(hist)
            ok = jax.vmap(lambda st, sj, m: lane(st, sj, m, counts, slots))(
                SEQ_T, SEQ_J, MASKS
            )
            return (valid == 1) & ok.any()

        return fn
