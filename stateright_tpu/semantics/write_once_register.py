"""Write-once register reference semantics (second distinct write fails).

Reference: ``WORegister`` at
``/root/reference/src/semantics/write_once_register.rs``.
"""

from __future__ import annotations

from .base import SequentialSpec


def WoWrite(value):
    return ("Write", value)


WO_READ = ("Read",)
WO_WRITE_OK = ("WriteOk",)
WO_WRITE_FAIL = ("WriteFail",)

_UNSET = ("Unset",)


def WoReadOk(value_option):
    """``value_option`` is None (unset) or ("Some", value)."""
    return ("ReadOk", value_option)


class WORegister(SequentialSpec):
    """Write succeeds when unset or equal to the current value; a second
    distinct write fails. Read returns None or ("Some", value)."""

    def __init__(self, value_option=None):
        # None or ("Some", value)
        self.value_option = value_option

    def invoke(self, op):
        if op[0] == "Write":
            if self.value_option is None or self.value_option == ("Some", op[1]):
                self.value_option = ("Some", op[1])
                return WO_WRITE_OK
            return WO_WRITE_FAIL
        if op == WO_READ:
            return WoReadOk(self.value_option)
        raise ValueError(f"unknown WO-register op: {op!r}")

    def is_valid_step(self, op, ret) -> bool:
        if op[0] == "Write":
            if ret == WO_WRITE_OK:
                if self.value_option is None:
                    self.value_option = ("Some", op[1])
                    return True
                return self.value_option == ("Some", op[1])
            if ret == WO_WRITE_FAIL:
                return (
                    self.value_option is not None
                    and self.value_option != ("Some", op[1])
                )
            return False
        if op == WO_READ and ret[0] == "ReadOk":
            return self.value_option == ret[1]
        return False

    def clone(self) -> "WORegister":
        return WORegister(self.value_option)

    def __stable_fields__(self):
        return ("WORegister", self.value_option)

    def __eq__(self, other):
        return (
            isinstance(other, WORegister)
            and self.value_option == other.value_option
        )

    def __hash__(self):
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self):
        return f"WORegister({self.value_option!r})"
