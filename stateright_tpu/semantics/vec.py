"""Stack (Vec) reference semantics: Push/Pop/Len.

Reference: ``/root/reference/src/semantics/vec.rs``.
"""

from __future__ import annotations

from .base import SequentialSpec


def Push(value):
    return ("Push", value)


POP = ("Pop",)
LEN = ("Len",)
PUSH_OK = ("PushOk",)


def PopOk(value_option):
    return ("PopOk", value_option)


def LenOk(length):
    return ("LenOk", length)


class VecSpec(SequentialSpec):
    """A stack: Push(v) -> PushOk; Pop -> PopOk(None | ("Some", v));
    Len -> LenOk(n)."""

    def __init__(self, items=()):
        self.items = list(items)

    def invoke(self, op):
        if op[0] == "Push":
            self.items.append(op[1])
            return PUSH_OK
        if op == POP:
            if self.items:
                return PopOk(("Some", self.items.pop()))
            return PopOk(None)
        if op == LEN:
            return LenOk(len(self.items))
        raise ValueError(f"unknown vec op: {op!r}")

    def clone(self) -> "VecSpec":
        return VecSpec(self.items)

    def __stable_fields__(self):
        return ("VecSpec", tuple(self.items))

    def __eq__(self, other):
        return isinstance(other, VecSpec) and self.items == other.items

    def __hash__(self):
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self):
        return f"VecSpec({self.items!r})"
