"""Sequential-consistency tester: like linearizability minus the real-time
constraints (only per-thread program order is preserved).

Reference: ``SequentialConsistencyTester`` at
``/root/reference/src/semantics/sequential_consistency.rs:55-284``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .base import ConsistencyTester, SequentialSpec


class SequentialConsistencyTester(ConsistencyTester):
    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        self.history_by_thread: Dict = {}  # thread -> list of (op, ret)
        self.in_flight_by_thread: Dict = {}  # thread -> op
        self.is_valid_history = True

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    def clone(self) -> "SequentialConsistencyTester":
        c = SequentialConsistencyTester(self.init_ref_obj.clone())
        c.history_by_thread = {
            t: list(h) for t, h in self.history_by_thread.items()
        }
        c.in_flight_by_thread = dict(self.in_flight_by_thread)
        c.is_valid_history = self.is_valid_history
        return c

    def on_invoke(self, thread_id, op) -> "SequentialConsistencyTester":
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, "
                f"op={self.in_flight_by_thread[thread_id]!r}, "
                f"history_by_thread={self.history_by_thread!r}"
            )
        self.in_flight_by_thread[thread_id] = op
        self.history_by_thread.setdefault(thread_id, [])
        return self

    def on_return(self, thread_id, ret) -> "SequentialConsistencyTester":
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}, "
                f"history={self.history_by_thread.get(thread_id, [])!r}"
            )
        op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread.setdefault(thread_id, []).append((op, ret))
        return self

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    def serialized_history(self) -> Optional[List[Tuple[object, object]]]:
        if not self.is_valid_history:
            return None
        remaining = {
            t: list(h) for t, h in sorted(self.history_by_thread.items())
        }
        in_flight = dict(sorted(self.in_flight_by_thread.items()))
        return _serialize([], self.init_ref_obj, remaining, in_flight)

    def __stable_fields__(self):
        return (
            "SequentialConsistencyTester",
            self.init_ref_obj,
            tuple(
                (t, tuple(h)) for t, h in sorted(self.history_by_thread.items())
            ),
            tuple(sorted(self.in_flight_by_thread.items())),
            self.is_valid_history,
        )

    def __eq__(self, other):
        return (
            isinstance(other, SequentialConsistencyTester)
            and self.init_ref_obj == other.init_ref_obj
            and self.history_by_thread == other.history_by_thread
            and self.in_flight_by_thread == other.in_flight_by_thread
            and self.is_valid_history == other.is_valid_history
        )

    def __hash__(self):
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self):
        return (
            f"SequentialConsistencyTester(init={self.init_ref_obj!r}, "
            f"history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, "
            f"valid={self.is_valid_history})"
        )


def _serialize(valid_history, ref_obj, remaining, in_flight):
    if all(not h for h in remaining.values()):
        return valid_history
    for thread_id in list(remaining.keys()):
        remaining_history = remaining[thread_id]
        if not remaining_history:
            # Case 1: maybe linearize an in-flight op at the end.
            if thread_id not in in_flight:
                continue
            op = in_flight[thread_id]
            next_ref_obj = ref_obj.clone()
            ret = next_ref_obj.invoke(op)
            next_in_flight = dict(in_flight)
            del next_in_flight[thread_id]
            result = _serialize(
                valid_history + [(op, ret)],
                next_ref_obj,
                remaining,
                next_in_flight,
            )
            if result is not None:
                return result
        else:
            # Case 2: consume the thread's next completed op.
            op, ret = remaining_history[0]
            next_ref_obj = ref_obj.clone()
            if not next_ref_obj.is_valid_step(op, ret):
                continue
            next_remaining = dict(remaining)
            next_remaining[thread_id] = remaining_history[1:]
            result = _serialize(
                valid_history + [(op, ret)],
                next_ref_obj,
                next_remaining,
                in_flight,
            )
            if result is not None:
                return result
    return None
