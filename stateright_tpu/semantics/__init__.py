"""Consistency semantics: sequential reference objects ("specs") and testers
that validate concurrent histories against a consistency model.

Reference: ``/root/reference/src/semantics.rs`` and submodules.
"""

from .base import ConsistencyTester, SequentialSpec
from .register import READ, Register, ReadOk, Write, WRITE_OK
from .write_once_register import (
    WORegister,
    WO_READ,
    WO_WRITE_FAIL,
    WO_WRITE_OK,
    WoReadOk,
    WoWrite,
)
from .vec import VecSpec, Push, POP, LEN, PUSH_OK, PopOk, LenOk
from .linearizability import LinearizabilityTester
from .sequential_consistency import SequentialConsistencyTester

__all__ = [
    "ConsistencyTester",
    "LinearizabilityTester",
    "READ",
    "ReadOk",
    "Register",
    "SequentialConsistencyTester",
    "SequentialSpec",
    "VecSpec",
    "WORegister",
    "WO_READ",
    "WO_WRITE_FAIL",
    "WO_WRITE_OK",
    "WRITE_OK",
    "WoReadOk",
    "WoWrite",
    "Write",
    "Push",
    "POP",
    "LEN",
    "PUSH_OK",
    "PopOk",
    "LenOk",
]
