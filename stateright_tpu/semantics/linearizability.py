"""Linearizability tester (Wing & Gong-style exhaustive serialization search).

Each invocation records the index of every other thread's last completed
operation; serialization rejects interleavings that violate those real-time
constraints. The ``always "linearizable"`` property evaluates
``serialized_history() is not None`` per state — exponential worst case; this
is the hot spot in register-style benchmarks. On the TPU backend this check is
kept on the host over drained batches (see SURVEY §7 hard parts).

Reference: ``LinearizabilityTester`` at
``/root/reference/src/semantics/linearizability.rs:57-312``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .base import ConsistencyTester, SequentialSpec


class LinearizabilityTester(ConsistencyTester):
    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        # thread -> list of (completed_map, op, ret); completed_map records,
        # at invocation time, each *other* thread's last completed op index.
        self.history_by_thread: Dict = {}
        # thread -> (completed_map, op)
        self.in_flight_by_thread: Dict = {}
        self.is_valid_history = True

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    def clone(self) -> "LinearizabilityTester":
        c = LinearizabilityTester(self.init_ref_obj.clone())
        c.history_by_thread = {
            t: list(h) for t, h in self.history_by_thread.items()
        }
        c.in_flight_by_thread = dict(self.in_flight_by_thread)
        c.is_valid_history = self.is_valid_history
        return c

    # -- recording -----------------------------------------------------------

    def on_invoke(self, thread_id, op) -> "LinearizabilityTester":
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            in_flight_op = self.in_flight_by_thread[thread_id][1]
            raise ValueError(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, op={in_flight_op!r}, "
                f"history_by_thread={self.history_by_thread!r}"
            )
        last_completed = tuple(
            sorted(
                (t, len(h) - 1)
                for t, h in self.history_by_thread.items()
                if t != thread_id and h
            )
        )
        self.in_flight_by_thread[thread_id] = (last_completed, op)
        self.history_by_thread.setdefault(thread_id, [])
        return self

    def on_return(self, thread_id, ret) -> "LinearizabilityTester":
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}, "
                f"history={self.history_by_thread.get(thread_id, [])!r}"
            )
        completed, op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread.setdefault(thread_id, []).append(
            (completed, op, ret)
        )
        return self

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    # -- serialization search ------------------------------------------------

    def serialized_history(self) -> Optional[List[Tuple[object, object]]]:
        """A total order of (op, ret) consistent with the reference object's
        semantics and the recorded real-time constraints, or None."""
        if not self.is_valid_history:
            return None
        # thread -> list of (orig_index, (completed_map, op, ret))
        remaining = {
            t: [(i, entry) for i, entry in enumerate(h)]
            for t, h in sorted(self.history_by_thread.items())
        }
        in_flight = dict(sorted(self.in_flight_by_thread.items()))
        return _serialize([], self.init_ref_obj, remaining, in_flight)

    # -- value semantics -----------------------------------------------------

    def __stable_fields__(self):
        return (
            "LinearizabilityTester",
            self.init_ref_obj,
            tuple(
                (t, tuple(h)) for t, h in sorted(self.history_by_thread.items())
            ),
            tuple(sorted(self.in_flight_by_thread.items())),
            self.is_valid_history,
        )

    def __eq__(self, other):
        return (
            isinstance(other, LinearizabilityTester)
            and self.init_ref_obj == other.init_ref_obj
            and self.history_by_thread == other.history_by_thread
            and self.in_flight_by_thread == other.in_flight_by_thread
            and self.is_valid_history == other.is_valid_history
        )

    def __hash__(self):
        from ..core.fingerprint import stable_hash

        return stable_hash(self.__stable_fields__())

    def __repr__(self):
        return (
            f"LinearizabilityTester(init={self.init_ref_obj!r}, "
            f"history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, "
            f"valid={self.is_valid_history})"
        )


def _violates_real_time(completed_map, remaining) -> bool:
    """True if some peer still has an unconsumed op at or before the index
    recorded as already-completed when this op was invoked."""
    for peer_id, min_peer_time in completed_map:
        ops = remaining.get(peer_id)
        if ops:
            next_peer_time = ops[0][0]
            if next_peer_time <= min_peer_time:
                return True
    return False


def _serialize(valid_history, ref_obj, remaining, in_flight):
    if all(not h for h in remaining.values()):
        return valid_history
    for thread_id in list(remaining.keys()):
        remaining_history = remaining[thread_id]
        if not remaining_history:
            # Case 1: no completed ops left; maybe linearize an in-flight op.
            if thread_id not in in_flight:
                continue
            completed_map, op = in_flight[thread_id]
            if _violates_real_time(completed_map, remaining):
                continue
            next_ref_obj = ref_obj.clone()
            ret = next_ref_obj.invoke(op)
            next_in_flight = dict(in_flight)
            del next_in_flight[thread_id]
            result = _serialize(
                valid_history + [(op, ret)],
                next_ref_obj,
                remaining,
                next_in_flight,
            )
            if result is not None:
                return result
        else:
            # Case 2: consume the thread's next completed op.
            _orig_index, (completed_map, op, ret) = remaining_history[0]
            if _violates_real_time(completed_map, remaining):
                continue
            next_ref_obj = ref_obj.clone()
            if not next_ref_obj.is_valid_step(op, ret):
                continue
            next_remaining = dict(remaining)
            next_remaining[thread_id] = remaining_history[1:]
            result = _serialize(
                valid_history + [(op, ret)],
                next_ref_obj,
                next_remaining,
                in_flight,
            )
            if result is not None:
                return result
    return None
