"""Sequential specifications and consistency testers.

Reference: ``SequentialSpec`` at ``/root/reference/src/semantics.rs:73-98``,
``ConsistencyTester`` at
``/root/reference/src/semantics/consistency_tester.rs:15-43``.
"""

from __future__ import annotations

from typing import Iterable, Tuple


class SequentialSpec:
    """A sequential "reference object" against which to validate the
    operational semantics of a concurrent system. Ops and returns are tagged
    tuples (e.g. ``("Write", v)`` -> ``("WriteOk",)``)."""

    def invoke(self, op) -> object:
        """Invokes an operation, mutating this reference object, and returns
        the resulting value."""
        raise NotImplementedError

    def is_valid_step(self, op, ret) -> bool:
        """Whether invoking ``op`` might result in ``ret`` (mutates)."""
        return self.invoke(op) == ret

    def is_valid_history(self, ops: Iterable[Tuple[object, object]]) -> bool:
        """Whether a sequential (op, ret) history is valid for this object."""
        return all(self.is_valid_step(op, ret) for op, ret in ops)

    def clone(self) -> "SequentialSpec":
        raise NotImplementedError


class ConsistencyTester:
    """Tests the consistency of a concurrent system against a
    ``SequentialSpec`` by recording operation invocations and returns.
    ``on_invoke``/``on_return`` raise ``ValueError`` on invalid histories
    (e.g. two in-flight operations for one thread)."""

    def on_invoke(self, thread_id, op) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id, ret) -> "ConsistencyTester":
        raise NotImplementedError

    def is_consistent(self) -> bool:
        raise NotImplementedError

    def on_invret(self, thread_id, op, ret) -> "ConsistencyTester":
        self.on_invoke(thread_id, op)
        return self.on_return(thread_id, ret)
